// Native M3TSZ float-mode codec: the host-side hot path.
//
// Role: the reference's performance-critical inner loops are hand-optimized
// Go (SURVEY.md §2.9); here the TPU kernels carry the batch path and this
// C++ library carries the host/serving path (single-series encodes on the
// ingest shell, block merges, and the measured CPU baseline for bench.py).
// Bit-identical to m3_tpu/encoding/m3tsz with int_optimized=False and a
// fixed time unit (same contract as the batched device kernels).
//
// Two codec generations live here:
//  - v1 (m3tsz_encode/m3tsz_decode/m3tsz_bench_roundtrip): byte-at-a-time
//    bit I/O, structurally the same as the reference Go ostream/istream
//    (/root/reference/src/dbnode/x/xio, encoding/ostream.go). This is the
//    FROZEN baseline bench.py measures as the stand-in for the reference's
//    single-core Go hot loop. Do not optimize it.
//  - v2 (m3tsz_encode_batch/m3tsz_decode_batch/m3tsz_roundtrip_batch):
//    the framework's CPU serving path — word-level (u64) bit buffers with
//    8-byte bswap flushes/loads and std::thread batching across series.
//    Produces byte-identical streams to v1.
//
// Build: g++ -O3 -shared -fPIC -pthread -o libm3tsz.so m3tsz.cpp

#include <cstdint>
#include <cstring>

#include <thread>
#include <vector>

// The v2 FastWriter/FastReader word path assumes a little-endian host
// (bswap64 + memcpy); a big-endian build would silently break the
// byte-identical stream contract v1 keeps, so refuse to compile there.
static_assert(__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__,
              "v2 batch codec requires a little-endian host");

namespace {

struct BitWriter {
    uint8_t* buf;
    int64_t cap;
    int64_t nbytes = 0;   // complete bytes flushed
    uint64_t acc = 0;     // pending bits, right-aligned
    int accbits = 0;
    bool overflow = false;

    void write(uint64_t v, int nbits) {  // MSB-first packing
        if (nbits == 0 || overflow) return;
        if (nbits < 64) v &= (1ull << nbits) - 1;
        while (nbits > 0) {
            int take = nbits;
            if (accbits + take > 56) take = 56 - accbits;  // keep room
            acc = (acc << take) | (take == 64 ? v : (v >> (nbits - take)));
            accbits += take;
            nbits -= take;
            if (nbits > 0) v &= (nbits == 64) ? ~0ull : ((1ull << nbits) - 1);
            while (accbits >= 8) {
                if (nbytes >= cap) { overflow = true; return; }
                accbits -= 8;
                buf[nbytes++] = (uint8_t)(acc >> accbits);
            }
        }
    }

    int64_t finish() {  // pad to byte boundary; returns total bytes
        if (accbits > 0) {
            if (nbytes >= cap) { overflow = true; return -1; }
            buf[nbytes++] = (uint8_t)(acc << (8 - accbits));
            accbits = 0;
        }
        return nbytes;
    }

    int64_t bitlen() const { return nbytes * 8 + accbits; }
};

struct BitReader {
    const uint8_t* buf;
    int64_t nbits;
    int64_t bitpos = 0;
    bool err = false;  // set on any out-of-bounds read; stream is invalid

    bool can(int n) const { return bitpos + n <= nbits; }

    uint64_t read(int n) {
        if (!can(n)) { err = true; bitpos = nbits; return 0; }
        // byte-window read: gather up to 9 bytes covering the span
        uint64_t out = 0;
        int64_t p = bitpos;
        bitpos += n;
        while (n > 0) {
            int64_t byte = p >> 3;
            int off = (int)(p & 7);
            int take = 8 - off;
            if (take > n) take = n;
            uint8_t b = buf[byte];
            out = (out << take) | (uint64_t)((uint8_t)(b << off) >> (8 - take));
            p += take;
            n -= take;
        }
        return out;
    }

    uint64_t peek(int n) {
        int64_t save = bitpos;
        uint64_t v = read(n);
        bitpos = save;
        return v;
    }
};

inline int clz64(uint64_t v) { return v ? __builtin_clzll(v) : 64; }
inline int ctz64(uint64_t v) { return v ? __builtin_ctzll(v) : 0; }

// delta-of-delta bucket scheme (reference scheme.go:44-52)
void write_dod(BitWriter& w, int64_t dod, int default_bits) {
    if (dod == 0) { w.write(0, 1); return; }
    if (dod >= -64 && dod <= 63) {
        w.write(0b10, 2); w.write((uint64_t)dod & 0x7F, 7);
    } else if (dod >= -256 && dod <= 255) {
        w.write(0b110, 3); w.write((uint64_t)dod & 0x1FF, 9);
    } else if (dod >= -2048 && dod <= 2047) {
        w.write(0b1110, 4); w.write((uint64_t)dod & 0xFFF, 12);
    } else {
        w.write(0b1111, 4);
        if (default_bits == 32) w.write((uint64_t)dod & 0xFFFFFFFFu, 32);
        else w.write((uint64_t)dod, 64);
    }
}

inline int64_t sign_extend(uint64_t v, int bits) {
    uint64_t sign = 1ull << (bits - 1);
    return (int64_t)((v ^ sign)) - (int64_t)sign;
}

}  // namespace

extern "C" {

// Encode one series; returns total bytes written (incl. EOS tail), -1 on
// overflow or misaligned start, -2 on dod overflow for 32-bit units.
int64_t m3tsz_encode(const int64_t* times, const uint64_t* vbits, int32_t n,
                     int64_t start, int64_t unit_ns, int32_t default_bits,
                     uint8_t* out, int64_t out_cap) {
    if (n <= 0 || unit_ns <= 0 || start % unit_ns != 0) return -1;
    memset(out, 0, (size_t)out_cap);
    BitWriter w{out, out_cap};
    w.write((uint64_t)start, 64);
    int64_t prev_t = start, prev_dt = 0;
    uint64_t prev_bits = 0, prev_xor = 0;
    for (int32_t i = 0; i < n; ++i) {
        int64_t dt = times[i] - prev_t;
        int64_t dod_ns = dt - prev_dt;
        int64_t dod = dod_ns / unit_ns;  // trunc toward zero (C++ semantics)
        if (default_bits == 32 && (dod < INT32_MIN || dod > INT32_MAX)) return -2;
        write_dod(w, dod, default_bits);
        prev_dt = dt;
        prev_t = times[i];

        uint64_t vb = vbits[i];
        if (i == 0) {
            w.write(vb, 64);
            prev_bits = vb;
            prev_xor = vb;
        } else {
            uint64_t x = vb ^ prev_bits;
            if (x == 0) {
                w.write(0, 1);
            } else {
                int pl = clz64(prev_xor), pt = ctz64(prev_xor);
                int cl = clz64(x), ct = ctz64(x);
                if (prev_xor != 0 && cl >= pl && ct >= pt) {
                    w.write(0b10, 2);
                    w.write(x >> pt, 64 - pl - pt);
                } else {
                    int m = 64 - cl - ct;
                    w.write(0b11, 2);
                    w.write((uint64_t)cl, 6);
                    w.write((uint64_t)(m - 1), 6);
                    w.write(x >> ct, m);
                }
            }
            prev_xor = x;
            prev_bits = vb;
        }
        if (w.overflow) return -1;
    }
    // end-of-stream marker: 9-bit opcode 0x100 + 2-bit value 0
    w.write(0x100, 9);
    w.write(0, 2);
    int64_t total = w.finish();
    if (w.overflow) return -1;
    return total;
}

// Decode one stream; returns datapoint count, -1 on error/marker.
int32_t m3tsz_decode(const uint8_t* data, int64_t len, int64_t unit_ns,
                     int32_t default_bits, int64_t* times, uint64_t* vbits,
                     int32_t max_points) {
    BitReader r{data, len * 8};
    if (!r.can(64)) return 0;
    int64_t prev_t = sign_extend(r.read(64), 64);
    int64_t prev_dt = 0;
    uint64_t prev_bits = 0, prev_xor = 0;
    int32_t count = 0;
    while (count < max_points) {
        if (r.can(11) && (r.peek(11) >> 2) == 0x100) {
            uint64_t marker = r.peek(11) & 3;
            if (marker == 0) break;   // EOS
            return -1;                 // host-path marker: not ours to decode
        }
        if (!r.can(1)) break;
        int64_t dod;
        if (r.read(1) == 0) {
            dod = 0;
        } else if (!r.can(1)) { break; }
        else if (r.read(1) == 0) {
            dod = sign_extend(r.read(7), 7);
        } else if (r.read(1) == 0) {
            dod = sign_extend(r.read(9), 9);
        } else if (r.read(1) == 0) {
            dod = sign_extend(r.read(12), 12);
        } else {
            dod = (default_bits == 32) ? sign_extend(r.read(32), 32)
                                       : sign_extend(r.read(64), 64);
        }
        prev_dt += dod * unit_ns;
        prev_t += prev_dt;

        if (count == 0) {
            if (!r.can(64)) return -1;
            prev_bits = r.read(64);
            prev_xor = prev_bits;
        } else {
            if (!r.can(1)) return -1;
            if (r.read(1) == 0) {
                prev_xor = 0;  // repeat value
            } else {
                if (!r.can(1)) return -1;
                if (r.read(1) == 0) {  // contained
                    int pl = clz64(prev_xor), pt = ctz64(prev_xor);
                    int m = 64 - pl - pt;
                    prev_xor = r.read(m) << pt;
                } else {  // uncontained
                    int lead = (int)r.read(6);
                    int m = (int)r.read(6) + 1;
                    int trail = 64 - lead - m;
                    if (trail < 0) return -1;  // corrupt: lead + m > 64
                    prev_xor = r.read(m) << trail;
                }
                prev_bits ^= prev_xor;
            }
        }
        if (r.err) break;  // truncated mid-datapoint: keep complete points
        times[count] = prev_t;
        vbits[count] = prev_bits;
        ++count;
    }
    return count;
}

// Batched round-trip driver for baseline measurement: encodes and decodes
// B series of T points entirely in native code (no per-series FFI cost).
// Returns total datapoints processed, or -1 on any error.
int64_t m3tsz_bench_roundtrip(const int64_t* times, const uint64_t* vbits,
                              int32_t B, int32_t T, int64_t start,
                              int64_t unit_ns, int32_t default_bits,
                              uint8_t* scratch, int64_t scratch_cap,
                              int64_t* out_times, uint64_t* out_vbits) {
    int64_t total = 0;
    for (int32_t b = 0; b < B; ++b) {
        int64_t nbytes = m3tsz_encode(times + (int64_t)b * T, vbits + (int64_t)b * T,
                                      T, start, unit_ns, default_bits,
                                      scratch, scratch_cap);
        if (nbytes < 0) return -1;
        int32_t n = m3tsz_decode(scratch, nbytes, unit_ns, default_bits,
                                 out_times, out_vbits, T);
        if (n != T) return -1;
        total += n;
    }
    return total;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// v2: word-level bit I/O + threaded batch drivers (the serving path).
// ---------------------------------------------------------------------------

namespace {

// MSB-first bit writer holding a 64-bit accumulator; whole words are flushed
// with one bswap+memcpy instead of v1's per-byte loop.
struct FastWriter {
    uint8_t* buf;
    int64_t cap;
    int64_t pos = 0;      // bytes flushed
    uint64_t acc = 0;     // pending bits, right-aligned
    int accbits = 0;      // 0..63 between put() calls
    bool ovf = false;

    inline void flush_word(uint64_t w) {
        if (pos + 8 <= cap) {
            w = __builtin_bswap64(w);
            memcpy(buf + pos, &w, 8);
            pos += 8;
        } else {
            ovf = true;
        }
    }

    inline void put(uint64_t v, int n) {  // n in 1..64
        if (n < 64) v &= (1ull << n) - 1;
        int space = 64 - accbits;
        if (n < space) {
            acc = (acc << n) | v;
            accbits += n;
            return;
        }
        int rem = n - space;  // 0..63
        flush_word(space == 64 ? v : (acc << space) | (v >> rem));
        acc = rem ? (v & ((1ull << rem) - 1)) : 0;
        accbits = rem;
    }

    int64_t finish() {  // pad to byte boundary; returns total bytes
        int nb = (accbits + 7) / 8;
        if (pos + nb > cap) { ovf = true; return -1; }
        uint64_t a = accbits ? (acc << (64 - accbits)) : 0;
        for (int i = 0; i < nb; ++i) {
            buf[pos++] = (uint8_t)(a >> 56);
            a <<= 8;
        }
        accbits = 0;
        return pos;
    }
};

// MSB-first bit reader doing unaligned 8-byte loads. The caller must
// guarantee 9 readable bytes past the last stream byte — only the batch
// drivers provide that slack (they own their buffers and pad the stride);
// there is NO padded single-stream entry point, so route single streams
// through m3tsz_decode_batch with B=1.
struct FastReader {
    const uint8_t* buf;
    int64_t nbits;
    int64_t bitpos = 0;
    bool err = false;

    inline bool can(int n) const { return bitpos + n <= nbits; }

    inline uint64_t read(int n) {  // n in 1..64
        if (bitpos + n > nbits) { err = true; bitpos = nbits; return 0; }
        int64_t byte = bitpos >> 3;
        int off = (int)(bitpos & 7);
        bitpos += n;
        uint64_t w;
        memcpy(&w, buf + byte, 8);
        w = __builtin_bswap64(w);
        if (off + n <= 64) return (w << off) >> (64 - n);
        int extra = off + n - 64;  // 1..7: spill into one more byte
        return ((w << off) >> (64 - n)) | ((uint64_t)buf[byte + 8] >> (8 - extra));
    }

    inline uint64_t peek(int n) {
        int64_t p = bitpos;
        bool e = err;
        uint64_t v = read(n);
        bitpos = p;
        err = e;
        return v;
    }
};

inline void write_dod_fast(FastWriter& w, int64_t dod, int default_bits) {
    if (dod == 0) { w.put(0, 1); return; }
    if (dod >= -64 && dod <= 63) {
        w.put((0b10ull << 7) | ((uint64_t)dod & 0x7F), 9);
    } else if (dod >= -256 && dod <= 255) {
        w.put((0b110ull << 9) | ((uint64_t)dod & 0x1FF), 12);
    } else if (dod >= -2048 && dod <= 2047) {
        w.put((0b1110ull << 12) | ((uint64_t)dod & 0xFFF), 16);
    } else if (default_bits == 32) {
        w.put((0b1111ull << 32) | ((uint64_t)dod & 0xFFFFFFFFull), 36);
    } else {
        w.put(0b1111, 4);
        w.put((uint64_t)dod, 64);
    }
}

int64_t encode_fast(const int64_t* times, const uint64_t* vbits, int32_t n,
                    int64_t start, int64_t unit_ns, int32_t default_bits,
                    uint8_t* out, int64_t out_cap) {
    // n == 0 is legal (start prefix + EOS only), matching the XLA encoder.
    if (n < 0 || unit_ns <= 0 || start % unit_ns != 0) return -1;
    FastWriter w{out, out_cap};
    w.put((uint64_t)start, 64);
    int64_t prev_t = start, prev_dt = 0;
    uint64_t prev_bits = 0, prev_xor = 0;
    for (int32_t i = 0; i < n; ++i) {
        int64_t dt = times[i] - prev_t;
        int64_t dod_ns = dt - prev_dt;
        int64_t dod = dod_ns / unit_ns;
        if (default_bits == 32 && (dod < INT32_MIN || dod > INT32_MAX)) return -2;
        write_dod_fast(w, dod, default_bits);
        prev_dt = dt;
        prev_t = times[i];

        uint64_t vb = vbits[i];
        if (i == 0) {
            w.put(vb, 64);
            prev_bits = vb;
            prev_xor = vb;
        } else {
            uint64_t x = vb ^ prev_bits;
            if (x == 0) {
                w.put(0, 1);
            } else {
                int pl = clz64(prev_xor), pt = ctz64(prev_xor);
                int cl = clz64(x), ct = ctz64(x);
                if (prev_xor != 0 && cl >= pl && ct >= pt) {
                    int m = 64 - pl - pt;
                    if (m <= 62)  // opcode + payload in one word
                        w.put((0b10ull << m) | (x >> pt), 2 + m);
                    else {
                        w.put(0b10, 2);
                        w.put(x >> pt, m);
                    }
                } else {
                    int m = 64 - cl - ct;
                    uint64_t hdr = (0b11ull << 12) | ((uint64_t)cl << 6)
                                   | (uint64_t)(m - 1);
                    if (m <= 50)  // 14-bit header + payload in one word
                        w.put((hdr << m) | (x >> ct), 14 + m);
                    else {
                        w.put(hdr, 14);
                        w.put(x >> ct, m);
                    }
                }
            }
            prev_xor = x;
            prev_bits = vb;
        }
        if (w.ovf) return -1;
    }
    w.put((0x100ull << 2), 11);  // EOS marker: 9-bit opcode + 2-bit value 0
    int64_t total = w.finish();
    if (w.ovf) return -1;
    return total;
}

int32_t decode_fast(const uint8_t* data, int64_t len, int64_t unit_ns,
                    int32_t default_bits, int64_t* times, uint64_t* vbits,
                    int32_t max_points) {
    FastReader r{data, len * 8};
    if (!r.can(64)) return 0;
    int64_t prev_t = sign_extend(r.read(64), 64);
    int64_t prev_dt = 0;
    uint64_t prev_bits = 0, prev_xor = 0;
    int32_t count = 0;
    while (count < max_points) {
        int64_t dod;
        // Fast path: classify the timestamp field from ONE 16-bit peek
        // (the '0'/'10'/'110'/'1110' short forms fit entirely; markers
        // lead with the reserved 9-bit '100000000' prefix). The
        // bit-by-bit fallback below handles the stream tail.
        if (r.can(16)) {
            uint64_t h = r.peek(16);
            if ((h >> 7) == 0x100) {      // marker opcode
                if (((h >> 5) & 3) == 0) break;  // EOS
                return -1;  // host-path marker: not ours to decode
            }
            if (!(h >> 15)) {
                r.bitpos += 1;
                dod = 0;
            } else if (!((h >> 14) & 1)) {
                dod = sign_extend((h >> 7) & 0x7F, 7);
                r.bitpos += 9;
            } else if (!((h >> 13) & 1)) {
                dod = sign_extend((h >> 4) & 0x1FF, 9);
                r.bitpos += 12;
            } else if (!((h >> 12) & 1)) {
                dod = sign_extend(h & 0xFFF, 12);
                r.bitpos += 16;
            } else {
                r.bitpos += 4;
                dod = (default_bits == 32) ? sign_extend(r.read(32), 32)
                                           : sign_extend(r.read(64), 64);
            }
        } else {
            if (r.can(11) && (r.peek(11) >> 2) == 0x100) {
                uint64_t marker = r.peek(11) & 3;
                if (marker == 0) break;   // EOS
                return -1;                 // host-path marker
            }
            if (!r.can(1)) break;
            if (r.read(1) == 0) {
                dod = 0;
            } else if (!r.can(1)) { break; }
            else if (r.read(1) == 0) {
                dod = sign_extend(r.read(7), 7);
            } else if (r.read(1) == 0) {
                dod = sign_extend(r.read(9), 9);
            } else if (r.read(1) == 0) {
                dod = sign_extend(r.read(12), 12);
            } else {
                dod = (default_bits == 32) ? sign_extend(r.read(32), 32)
                                           : sign_extend(r.read(64), 64);
            }
        }
        prev_dt += dod * unit_ns;
        prev_t += prev_dt;

        if (count == 0) {
            if (!r.can(64)) return -1;
            prev_bits = r.read(64);
            prev_xor = prev_bits;
        } else if (r.can(64)) {
            // fast path: header AND payload from one 64-bit peek
            // ('0' | '10'+m | '11'+6 lead+6 (m-1)+m); only payloads too
            // long to share the word (m > 62 / m > 50) pay a second read
            uint64_t vw = r.peek(64);
            if (!(vw >> 63)) {
                r.bitpos += 1;
                prev_xor = 0;  // repeat value
            } else if (!((vw >> 62) & 1)) {  // contained
                int pl = clz64(prev_xor), pt = ctz64(prev_xor);
                int m = 64 - pl - pt;
                if (m <= 0) return -1;  // corrupt: see fallback comment
                if (m <= 62) {  // 2 + m <= 64: inside the peeked word
                    prev_xor = ((vw << 2) >> (64 - m)) << pt;
                    r.bitpos += 2 + m;
                } else {
                    r.bitpos += 2;
                    prev_xor = r.read(m) << pt;
                }
                prev_bits ^= prev_xor;
            } else {  // uncontained
                int lead = (int)((vw >> 56) & 0x3F);
                int m = (int)((vw >> 50) & 0x3F) + 1;
                int trail = 64 - lead - m;
                if (trail < 0) return -1;
                if (m <= 50) {  // 14 + m <= 64: inside the peeked word
                    prev_xor = ((vw << 14) >> (64 - m)) << trail;
                    r.bitpos += 14 + m;
                } else {
                    r.bitpos += 14;
                    prev_xor = r.read(m) << trail;
                }
                prev_bits ^= prev_xor;
            }
        } else {
            if (!r.can(1)) return -1;
            if (r.read(1) == 0) {
                prev_xor = 0;  // repeat value
            } else {
                if (!r.can(1)) return -1;
                if (r.read(1) == 0) {  // contained
                    int pl = clz64(prev_xor), pt = ctz64(prev_xor);
                    int m = 64 - pl - pt;
                    // m == 0 (prev_xor == 0) only on corrupt streams: a
                    // well-formed encoder emits the repeat opcode then.
                    // read(0) would shift by 64 (UB); reject instead.
                    if (m <= 0) return -1;
                    prev_xor = r.read(m) << pt;
                } else {  // uncontained
                    int lead = (int)r.read(6);
                    int m = (int)r.read(6) + 1;
                    int trail = 64 - lead - m;
                    if (trail < 0) return -1;
                    prev_xor = r.read(m) << trail;
                }
                prev_bits ^= prev_xor;
            }
        }
        if (r.err) break;
        times[count] = prev_t;
        vbits[count] = prev_bits;
        ++count;
    }
    return count;
}

// Run fn(b) over b in [0, B) on nthreads threads in contiguous chunks.
template <typename F>
void parallel_over(int32_t B, int32_t nthreads, F fn) {
    if (nthreads <= 1 || B <= 1) {
        for (int32_t b = 0; b < B; ++b) fn(b);
        return;
    }
    if (nthreads > B) nthreads = B;
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int32_t t = 0; t < nthreads; ++t) {
        int64_t lo = (int64_t)B * t / nthreads;
        int64_t hi = (int64_t)B * (t + 1) / nthreads;
        ts.emplace_back([lo, hi, &fn] {
            for (int64_t b = lo; b < hi; ++b) fn((int32_t)b);
        });
    }
    for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Encode B series into out (stride bytes per series, which must include
// >= 9 bytes of slack past the worst-case stream for the decoder's
// unaligned loads). Series b encodes n_points[b] points from row b of the
// [B, T] input (n_points == nullptr means all T). out_lens[b] = stream
// bytes, or <0 on error. Returns 0, or -1 if any series failed.
int64_t m3tsz_encode_batch(const int64_t* times, const uint64_t* vbits,
                           int32_t B, int32_t T, const int64_t* starts,
                           const int32_t* n_points,
                           int64_t unit_ns, int32_t default_bits,
                           uint8_t* out, int64_t stride, int64_t* out_lens,
                           int32_t nthreads) {
    parallel_over(B, nthreads, [&](int32_t b) {
        int32_t n = n_points ? n_points[b] : T;
        if (n > T) n = T;
        out_lens[b] = encode_fast(times + (int64_t)b * T, vbits + (int64_t)b * T,
                                  n, starts[b], unit_ns, default_bits,
                                  out + (int64_t)b * stride, stride);
    });
    for (int32_t b = 0; b < B; ++b)
        if (out_lens[b] < 0) return -1;
    return 0;
}

// Decode B streams (stride bytes apart, lens[b] bytes each; the buffer must
// have >= 9 readable bytes past each stream end) into [B, T] outputs.
// out_ns[b] = decoded point count, or <0 on error. Returns 0 or -1.
int64_t m3tsz_decode_batch(const uint8_t* streams, const int64_t* lens,
                           int64_t stride, int32_t B, int64_t unit_ns,
                           int32_t default_bits, int64_t* times,
                           uint64_t* vbits, int32_t T, int32_t* out_ns,
                           int32_t nthreads) {
    parallel_over(B, nthreads, [&](int32_t b) {
        out_ns[b] = decode_fast(streams + (int64_t)b * stride, lens[b],
                                unit_ns, default_bits,
                                times + (int64_t)b * T, vbits + (int64_t)b * T,
                                T);
    });
    for (int32_t b = 0; b < B; ++b)
        if (out_ns[b] < 0) return -1;
    return 0;
}

// Threaded encode+decode round trip over [B, T] input: the v2 serving-path
// throughput measurement. Each thread owns scratch (stream + decode output)
// so the work is embarrassingly parallel. Writes the LAST series' decoded
// points into out_times/out_vbits (correctness probe). Returns total
// datapoints processed, or -1 on any error.
int64_t m3tsz_roundtrip_batch(const int64_t* times, const uint64_t* vbits,
                              int32_t B, int32_t T, int64_t start,
                              int64_t unit_ns, int32_t default_bits,
                              int64_t* out_times, uint64_t* out_vbits,
                              int32_t nthreads) {
    int64_t cap = 8 + ((int64_t)T * 146 + 11) / 8 + 32;
    std::vector<int64_t> errs(nthreads > 0 ? nthreads : 1, 0);
    if (nthreads <= 1) nthreads = 1;
    if (nthreads > B) nthreads = B > 0 ? B : 1;
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    for (int32_t t = 0; t < nthreads; ++t) {
        int64_t lo = (int64_t)B * t / nthreads;
        int64_t hi = (int64_t)B * (t + 1) / nthreads;
        ts.emplace_back([&, t, lo, hi] {
            std::vector<uint8_t> scratch((size_t)cap);
            std::vector<int64_t> dt((size_t)T);
            std::vector<uint64_t> dv((size_t)T);
            for (int64_t b = lo; b < hi; ++b) {
                int64_t nbytes = encode_fast(
                    times + b * T, vbits + b * T, T, start, unit_ns,
                    default_bits, scratch.data(), cap);
                if (nbytes < 0) { errs[t] = 1; return; }
                int32_t n = decode_fast(scratch.data(), nbytes, unit_ns,
                                        default_bits, dt.data(), dv.data(), T);
                if (n != T) { errs[t] = 1; return; }
                if (b == B - 1) {
                    memcpy(out_times, dt.data(), (size_t)T * 8);
                    memcpy(out_vbits, dv.data(), (size_t)T * 8);
                }
            }
        });
    }
    for (auto& th : ts) th.join();
    for (int64_t e : errs)
        if (e) return -1;
    return (int64_t)B * T;
}

}  // extern "C"
