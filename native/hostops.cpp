// Native CPU host ops for the aggregation / temporal-math serving paths.
//
// Role: the C++ stand-in for the reference's hand-optimized Go hot loops on
// hosts without an accelerator — the same architecture slot the native
// m3tsz batch codec fills for encode/decode (SURVEY.md §2.9: native host
// layer where Python latency would dominate). Two kinds of entry point:
//
//  * Serving-path kernels, dispatched by m3_tpu/ops/windowed_agg.py and
//    m3_tpu/query/windows.py when no accelerator is live:
//      - m3_agg_groups: columnar grouped aggregation over (elem, window)
//        keys (radix-sorted, one linear stats pass) — the flush reduction
//        behind aggregator.Aggregator.flush. Mirrors the semantics of the
//        reference's streaming accumulators
//        (/root/reference/src/aggregator/aggregation/counter.go:31-139)
//        computed batch-at-once instead of per-sample.
//      - m3_rate_csr: columnar extrapolated rate/increase/delta over CSR
//        series (pointer-walk windows, row-local reset adjustment) —
//        upstream Prometheus extrapolatedRate semantics, identical math to
//        the numpy path in m3_tpu/query/windows.py
//        (/root/reference/src/query/functions/temporal/rate.go role).
//
//  * Measured scalar baselines for bench_all (reference cost-model
//    stand-ins, the config-#1 methodology):
//      - m3_agg_baseline_scalar: per-sample string-keyed entry lookup +
//        per-entry mutex + accumulator update — the reference aggregator's
//        AddUntimed hot loop shape (aggregator/aggregator/map.go entry
//        lookup, entry.go lock, aggregation/counter.go update).
//      - m3_rate_baseline_scalar: per-(series, step) window re-scan with
//        in-window reset detection — the prometheus/reference engine shape
//        (each output step re-iterates its window's samples).
//
// Both baselines compute the same outputs as the serving kernels so the
// bench can assert correctness instead of racing a strawman.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr double kNS = 1e9;

// ---------------------------------------------------------------------------
// LSD radix sort of indices by a u64 key (stable). Digit width 8.
// ---------------------------------------------------------------------------

void radix_sort_indices(const std::vector<uint64_t>& keys,
                        std::vector<uint32_t>& idx,
                        std::vector<uint32_t>& scratch,
                        uint64_t key_max) {
    const size_t n = idx.size();
    int passes = 0;
    while (key_max) { passes++; key_max >>= 8; }
    if (passes == 0) return;
    uint32_t* src = idx.data();
    uint32_t* dst = scratch.data();
    for (int p = 0; p < passes; p++) {
        const int shift = p * 8;
        size_t count[257] = {0};
        for (size_t i = 0; i < n; i++)
            count[((keys[src[i]] >> shift) & 0xff) + 1]++;
        for (int d = 0; d < 256; d++) count[d + 1] += count[d];
        for (size_t i = 0; i < n; i++)
            dst[count[(keys[src[i]] >> shift) & 0xff]++] = src[i];
        std::swap(src, dst);
    }
    if (src != idx.data())
        memcpy(idx.data(), src, n * sizeof(uint32_t));
}

int bits_for(uint64_t range) {
    int b = 0;
    while (range) { b++; range >>= 1; }
    return b;
}

template <typename F>
void parallel_rows(int64_t n, int nthreads, F fn) {
    if (nthreads <= 1 || n < 2) {
        for (int64_t i = 0; i < n; i++) fn(i);
        return;
    }
    std::vector<std::thread> ts;
    int64_t chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; t++) {
        int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
        if (lo >= hi) break;
        ts.emplace_back([=]() { for (int64_t i = lo; i < hi; i++) fn(i); });
    }
    for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// Columnar grouped aggregation: group rows by (elem, window), compute every
// base statistic per group. Rows within a group keep append order (stable
// sort), so "last" = the row with max (time, append index) — the reference
// gauge lastAt tiebreak. Returns G (#groups) or -1 on error.
// All out_* arrays must hold n elements (G <= n); out_offsets n+1.
// want_sorted != 0 additionally fills out_vq with values sorted ascending
// WITHIN each group (quantile extraction input).
int64_t m3_agg_groups(
    const int64_t* e, const int64_t* w, const double* v, const int64_t* t,
    int64_t n, int32_t want_sorted,
    int64_t* out_e, int64_t* out_w,
    double* out_count, double* out_sum, double* out_sumsq,
    double* out_min, double* out_max, double* out_mean,
    double* out_last, double* out_stdev,
    double* out_vq, int64_t* out_offsets) {
    if (n <= 0) { out_offsets[0] = 0; return 0; }
    if (n > INT32_MAX) return -1;

    int64_t e_min = e[0], e_max = e[0], w_min = w[0], w_max = w[0];
    for (int64_t i = 1; i < n; i++) {
        e_min = std::min(e_min, e[i]); e_max = std::max(e_max, e[i]);
        w_min = std::min(w_min, w[i]); w_max = std::max(w_max, w[i]);
    }
    // ranges as UNSIGNED subtraction: adversarial ids spanning most of
    // int64 would overflow a signed max-min (UB); u64 wraparound is
    // defined and yields the correct distance
    const uint64_t e_range = (uint64_t)e_max - (uint64_t)e_min;
    const uint64_t w_range = (uint64_t)w_max - (uint64_t)w_min;
    const int wbits = bits_for(w_range);

    std::vector<uint32_t> idx(n), scratch(n);
    for (int64_t i = 0; i < n; i++) idx[i] = (uint32_t)i;

    // wbits == 64 must take the comparison sort: "<< wbits" and
    // "1ull << wbits" are UB at 64 even when the packed key would fit
    if (wbits < 64 && bits_for(e_range) + wbits <= 64) {
        std::vector<uint64_t> keys(n);
        for (int64_t i = 0; i < n; i++)
            keys[i] = (((uint64_t)e[i] - (uint64_t)e_min) << wbits) |
                      ((uint64_t)w[i] - (uint64_t)w_min);
        radix_sort_indices(keys, idx, scratch,
                           (e_range << wbits) | ((1ull << wbits) - 1));
    } else {
        std::stable_sort(idx.begin(), idx.end(),
                         [&](uint32_t a, uint32_t b) {
                             if (e[a] != e[b]) return e[a] < e[b];
                             return w[a] < w[b];
                         });
    }

    // one linear pass over the sorted order
    int64_t G = -1;
    int64_t cur_e = 0, cur_w = 0;
    double cnt = 0, s1 = 0, s2 = 0, mn = 0, mx = 0, last_v = 0;
    int64_t last_t = 0; uint32_t last_i = 0;
    auto close_group = [&]() {
        if (G < 0) return;
        out_count[G] = cnt; out_sum[G] = s1; out_sumsq[G] = s2;
        out_min[G] = mn; out_max[G] = mx;
        double mean = s1 / cnt;
        out_mean[G] = mean;
        out_last[G] = last_v;
        double var = s2 / cnt - mean * mean;
        out_stdev[G] = std::sqrt(var > 0 ? var : 0.0);
    };
    for (int64_t k = 0; k < n; k++) {
        const uint32_t i = idx[k];
        if (G < 0 || e[i] != cur_e || w[i] != cur_w) {
            close_group();
            G++;
            cur_e = e[i]; cur_w = w[i];
            out_e[G] = cur_e; out_w[G] = cur_w;
            out_offsets[G] = k;
            cnt = 0; s1 = 0; s2 = 0;
            mn = v[i]; mx = v[i];
            last_v = v[i]; last_t = t[i]; last_i = i;
        }
        const double x = v[i];
        cnt += 1.0; s1 += x; s2 += x * x;
        if (x < mn) mn = x;
        if (x > mx) mx = x;
        // last by (time, append index): stable sort preserves append order,
        // but out-of-order timestamps within a group need the explicit max
        if (t[i] > last_t || (t[i] == last_t && i >= last_i)) {
            last_t = t[i]; last_i = i; last_v = x;
        }
    }
    close_group();
    G++;
    out_offsets[G] = n;

    if (want_sorted) {
        for (int64_t k = 0; k < n; k++) out_vq[k] = v[idx[k]];
        for (int64_t g = 0; g < G; g++)
            std::sort(out_vq + out_offsets[g], out_vq + out_offsets[g + 1]);
    }
    return G;
}

// Reference-cost-model scalar baseline: per-sample string-keyed entry
// lookup + per-entry lock + streaming accumulator update, then a flush
// walk emitting each (entry, window) sum. ids = concatenated id bytes with
// id_off[n+1] boundaries (the UNRESOLVED metric IDs the reference hashes on
// every add — aggregator/aggregator/map.go). Returns the total of all
// window sums (correctness checksum) or NaN on error.
double m3_agg_baseline_scalar(
    const char* ids, const int64_t* id_off, const int64_t* w,
    const double* v, int64_t n) {
    struct WinStats {
        int64_t w;
        double cnt = 0, sum = 0, sumsq = 0, mn = 0, mx = 0, last = 0;
    };
    struct Entry {
        std::mutex mu;
        std::vector<WinStats> wins;  // reference keeps per-resolution
                                     // windows in a small list
    };
    std::unordered_map<std::string, Entry*> map;
    map.reserve((size_t)(n / 4 + 16));
    std::vector<Entry*> owned;
    owned.reserve((size_t)(n / 4 + 16));

    for (int64_t i = 0; i < n; i++) {
        std::string id(ids + id_off[i], (size_t)(id_off[i + 1] - id_off[i]));
        auto it = map.find(id);
        Entry* ent;
        if (it == map.end()) {
            ent = new Entry();
            owned.push_back(ent);
            map.emplace(std::move(id), ent);
        } else {
            ent = it->second;
        }
        std::lock_guard<std::mutex> lk(ent->mu);
        WinStats* ws = nullptr;
        for (auto rit = ent->wins.rbegin(); rit != ent->wins.rend(); ++rit)
            if (rit->w == w[i]) { ws = &*rit; break; }
        if (!ws) {
            ent->wins.push_back(WinStats{w[i]});
            ws = &ent->wins.back();
            ws->mn = v[i]; ws->mx = v[i];
        }
        const double x = v[i];
        ws->cnt += 1.0; ws->sum += x; ws->sumsq += x * x;
        if (x < ws->mn) ws->mn = x;
        if (x > ws->mx) ws->mx = x;
        ws->last = x;
    }
    double total = 0;
    for (Entry* ent : owned) {
        for (const auto& ws : ent->wins) total += ws.sum;
        delete ent;
    }
    return total;
}

// Columnar extrapolated rate/increase/delta over CSR series. Identical
// math (same operation order) to the numpy host path in
// m3_tpu/query/windows.py::extrapolated_rate, which mirrors upstream
// Prometheus extrapolatedRate. eval_ts must be ascending (the engine's
// step grid always is). out is [S, K] row-major.
void m3_rate_csr(
    const int64_t* times, const double* values, const int64_t* offsets,
    int64_t S, const int64_t* eval_ts, int64_t K, int64_t range_ns,
    int32_t is_counter, int32_t is_rate, int32_t nthreads, double* out) {
    const double nan = std::numeric_limits<double>::quiet_NaN();
    const double range_s = (double)range_ns / kNS;
    parallel_rows(S, nthreads, [&](int64_t s) {
        const int64_t a = offsets[s], b = offsets[s + 1];
        double* row_out = out + s * K;
        // row-local reset adjustment: adj[i] = v[i] + cumulative drops
        std::vector<double> adj;
        if (is_counter) {
            adj.resize((size_t)(b - a));
            double cum = 0;
            for (int64_t i = a; i < b; i++) {
                if (i > a && values[i] < values[i - 1]) cum += values[i - 1];
                adj[(size_t)(i - a)] = values[i] + cum;
            }
        }
        int64_t lo = a, hi = a;
        for (int64_t k = 0; k < K; k++) {
            const int64_t ts = eval_ts[k];
            const int64_t ws = ts - range_ns;
            while (hi < b && times[hi] <= ts) hi++;
            while (lo < b && times[lo] <= ws) lo++;
            const int64_t count = hi - lo;
            if (count < 2) { row_out[k] = nan; continue; }
            const double first_v = is_counter ? adj[(size_t)(lo - a)]
                                              : values[lo];
            const double last_v = is_counter ? adj[(size_t)(hi - 1 - a)]
                                             : values[hi - 1];
            const double raw_first = values[lo];
            const double first_t = (double)times[lo];
            const double last_t = (double)times[hi - 1];
            double result = last_v - first_v;
            const double sampled = (last_t - first_t) / kNS;
            if (!(sampled > 0)) { row_out[k] = nan; continue; }
            double dur_start = (first_t - (double)ws) / kNS;
            double dur_end = ((double)ts - last_t) / kNS;
            const double avg = sampled / (double)(count - 1);
            const double thr = avg * 1.1;
            if (is_counter && result > 0 && raw_first >= 0) {
                const double dur_zero = sampled * (raw_first / result);
                if (dur_zero < dur_start) dur_start = dur_zero;
            }
            if (dur_start >= thr) dur_start = avg / 2;
            if (dur_end >= thr) dur_end = avg / 2;
            const double extrap = sampled + dur_start + dur_end;
            const double factor = extrap / sampled;
            double o = result * factor;
            if (is_rate) o = o / range_s;
            row_out[k] = o;
        }
    });
}

// Reference-cost-model scalar baseline: each (series, step) re-scans its
// window's samples (binary-searched bounds, in-window reset detection) —
// the per-step iteration shape of the prometheus engine / reference
// temporal ops. Computes the same outputs as m3_rate_csr.
void m3_rate_baseline_scalar(
    const int64_t* times, const double* values, const int64_t* offsets,
    int64_t S, const int64_t* eval_ts, int64_t K, int64_t range_ns,
    int32_t is_counter, int32_t is_rate, double* out) {
    const double nan = std::numeric_limits<double>::quiet_NaN();
    const double range_s = (double)range_ns / kNS;
    for (int64_t s = 0; s < S; s++) {
        const int64_t a = offsets[s], b = offsets[s + 1];
        double* row_out = out + s * K;
        for (int64_t k = 0; k < K; k++) {
            const int64_t ts = eval_ts[k];
            const int64_t ws = ts - range_ns;
            const int64_t* lo_p = std::upper_bound(times + a, times + b, ws);
            const int64_t* hi_p = std::upper_bound(lo_p, times + b, ts);
            const int64_t lo = lo_p - times, hi = hi_p - times;
            const int64_t count = hi - lo;
            if (count < 2) { row_out[k] = nan; continue; }
            // in-window scan: reset-adjusted delta from first to last
            double cum = 0;
            if (is_counter)
                for (int64_t i = lo + 1; i < hi; i++)
                    if (values[i] < values[i - 1]) cum += values[i - 1];
            const double raw_first = values[lo];
            double result = (values[hi - 1] + cum) - raw_first;
            const double first_t = (double)times[lo];
            const double last_t = (double)times[hi - 1];
            const double sampled = (last_t - first_t) / kNS;
            if (!(sampled > 0)) { row_out[k] = nan; continue; }
            double dur_start = (first_t - (double)ws) / kNS;
            double dur_end = ((double)ts - last_t) / kNS;
            const double avg = sampled / (double)(count - 1);
            const double thr = avg * 1.1;
            if (is_counter && result > 0 && raw_first >= 0) {
                const double dur_zero = sampled * (raw_first / result);
                if (dur_zero < dur_start) dur_start = dur_zero;
            }
            if (dur_start >= thr) dur_start = avg / 2;
            if (dur_end >= thr) dur_end = avg / 2;
            const double extrap = sampled + dur_start + dur_end;
            const double factor = extrap / sampled;
            double o = result * factor;
            if (is_rate) o = o / range_s;
            row_out[k] = o;
        }
    }
}

}  // extern "C"
