# repo-local tooling package (makes `python -m tools.m3lint` work from
# the repo root without installing anything)
