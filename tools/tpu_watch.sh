#!/bin/bash
# Background tunnel watcher: probe every ~3 min; when a terminal answers,
# immediately run the staged measurement (tpu_measure.py) under a bounded
# timeout. Stops for good once a complete result is recorded.
cd "$(dirname "$0")/.." || exit 1
LOG=tpu_watch.log
echo "=== tpu_watch start $(date -u +%H:%M:%S) ===" >> "$LOG"
while true; do
  if python -c "
import json,sys
try:
  d=json.load(open('tpu_measure_out.json'))
  sys.exit(0 if d.get('result')=='complete' else 1)
except Exception:
  sys.exit(1)
"; then
    echo "[$(date -u +%H:%M:%S)] complete result recorded; watcher exiting" >> "$LOG"
    exit 0
  fi
  if python m3_tpu/utils/tpu_preflight.py >> "$LOG" 2>&1; then
    echo "[$(date -u +%H:%M:%S)] TUNNEL LIVE — running staged measurement" >> "$LOG"
    timeout 900 python tpu_measure.py >> "$LOG" 2>&1
    echo "[$(date -u +%H:%M:%S)] measurement attempt rc=$? " >> "$LOG"
  fi
  sleep 170
done
