import sys

from tools.m3lint.engine import main

if __name__ == "__main__":
    sys.exit(main())
