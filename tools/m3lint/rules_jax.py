"""JAX jit-purity and recompile-hazard rules (rule family ``jax-*``).

The checker finds the module's *traced set*: functions that are
``jax.jit``/``jax.vmap`` roots (decorated, wrapped in
``functools.partial(jax.jit, ...)``, or assigned ``f = jax.jit(g)``) plus
everything they reach through intra-module calls.  Code in the traced set
runs under a tracer: Python-side effects execute ONCE at trace time and
are then baked into (or silently absent from) every cached executable —
the class of bug whole-query compilation (ROADMAP #2) multiplies.

``jax-impure-call``       randomness / wall-clock / uuid / env reads
                          inside traced code: trace-time constants
                          masquerading as per-call values
``jax-global-mutation``   ``global`` writes or mutation of module-level
                          containers inside traced code: runs once at
                          trace time, never again
``jax-host-materialize``  ``np.*(param)`` / ``float(param)`` /
                          ``param.item()`` on a *non-static* parameter of
                          a traced function: forces device→host sync or
                          a ConcretizationTypeError under jit
``jax-jit-per-call``      ``jax.jit``/``vmap`` constructed inside a
                          plain function body with no cache around it: a
                          fresh traced callable (and XLA compile) per
                          invocation — the recompile storm PR 6's
                          jit_tracker can only observe after the fact.
                          Also flags per-eval ``jax.sharding.Mesh`` /
                          ``NamedSharding`` construction (the sharded
                          compute plane's twin hazard: a mesh rebuilt
                          per query defeats jit's C++ dispatch fast
                          path, and a device-order drift mints fresh
                          executable cache keys — build them once in an
                          lru_cache factory, parallel/mesh.py style)
``jax-varying-static``    calling a jitted function in a loop with an
                          argument sliced by the loop variable (or a
                          per-iteration ``len()``): every iteration is a
                          new shape/static bucket, every bucket a compile

Recognized caching idioms that do NOT flag a jit construction: enclosing
function decorated ``functools.lru_cache``/``cache``; result stored into
a subscript (``_CACHE[key] = jax.jit(...)``) or via ``.setdefault``;
construction at module scope; construction inside the traced set itself
(tracing a vmap during a trace is one program, not one per call).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.m3lint.engine import attr_chain as _attr_chain
from tools.m3lint.engine import Finding, Module, Project

RULES = {
    "jax-impure-call": "impure host call inside jit-traced code",
    "jax-global-mutation": "global/module state mutated inside jit-traced code",
    "jax-host-materialize": "numpy/host materialization of a traced value",
    "jax-jit-per-call": "jit/vmap constructed per call (recompile storm)",
    "jax-varying-static": "jitted call with per-iteration shape/static args",
    "inv-jit-tracked": "jitted program called outside a jit_tracker",
}

_IMPURE_CALLS = {
    "time.time", "time.time_ns", "time.perf_counter", "time.monotonic",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "os.urandom", "uuid.uuid4", "os.environ.get", "os.getenv",
}
_IMPURE_OWNERS = ("random", "np.random", "numpy.random")
_MUTATORS = {"append", "add", "update", "pop", "clear", "extend", "insert",
             "setdefault", "remove", "discard", "popitem", "appendleft"}


def _is_jit_name(chain: str | None) -> bool:
    return chain in ("jit", "jax.jit")


def _is_vmap_name(chain: str | None) -> bool:
    return chain in ("vmap", "jax.vmap", "pmap", "jax.pmap")


def _is_sharding_ctor(chain: str | None) -> bool:
    """Mesh/NamedSharding constructors in any in-tree spelling."""
    return chain in ("Mesh", "jax.sharding.Mesh", "sharding.Mesh",
                     "NamedSharding", "jax.NamedSharding",
                     "jax.sharding.NamedSharding", "sharding.NamedSharding")


def _static_params(fn: ast.FunctionDef) -> set[str]:
    """Parameter names declared static via partial(jax.jit,
    static_argnames=...) / static_argnums=... decorators."""
    statics: set[str] = set()
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        inner = dec.args[0] if dec.args else None
        inner_chain = _attr_chain(inner) if inner is not None else None
        if not (_is_jit_name(_attr_chain(dec.func)) or
                (_attr_chain(dec.func) or "").endswith("partial")
                and _is_jit_name(inner_chain)):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        statics.add(el.value)
            elif kw.arg == "static_argnums":
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int) and \
                            el.value < len(args):
                        statics.add(args[el.value])
    return statics


@dataclass
class _FnRec:
    node: ast.FunctionDef
    qual: str
    is_root: bool = False
    statics: set = field(default_factory=set)
    calls: set = field(default_factory=set)     # resolved local callee quals
    parent: str | None = None                   # enclosing function qual


class _DefCollector(ast.NodeVisitor):
    """Pass 1: every function (incl. nested), decorator jit roots, and
    module-level names.  Two passes so forward references resolve — a
    jitted dispatcher happily calls helpers defined below it."""

    def __init__(self):
        self.fns: dict[str, _FnRec] = {}
        self.jitted_names: set[str] = set()   # names bound to jitted callables
        self._stack: list[str] = []
        self.module_names: set[str] = set()

    def _qual(self, name: str) -> str:
        return ".".join(self._stack + [name]) if self._stack else name

    def visit_Module(self, node):
        for child in node.body:
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    if isinstance(t, ast.Name):
                        self.module_names.add(t.id)
            elif isinstance(child, (ast.FunctionDef, ast.ClassDef)):
                self.module_names.add(child.name)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        rec = _FnRec(node=node, qual=qual,
                     parent=self._stack[-1] if self._stack else None)
        rec.statics = _static_params(node)
        for dec in node.decorator_list:
            chain = _attr_chain(dec)
            if _is_jit_name(chain) or _is_vmap_name(chain):
                rec.is_root = True
            elif isinstance(dec, ast.Call):
                dchain = _attr_chain(dec.func)
                if _is_jit_name(dchain) or _is_vmap_name(dchain):
                    rec.is_root = True
                elif (dchain or "").endswith("partial") and dec.args and \
                        (_is_jit_name(_attr_chain(dec.args[0])) or
                         _is_vmap_name(_attr_chain(dec.args[0]))):
                    rec.is_root = True
        self.fns[qual] = rec
        if rec.is_root:
            self.jitted_names.add(node.name)
        self._stack.append(qual)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


class _CallCollector(ast.NodeVisitor):
    """Pass 2: the intra-module call graph plus jit(f)/vmap(f) roots,
    resolved against the COMPLETE function table from pass 1."""

    def __init__(self, defs: _DefCollector):
        self.d = defs
        self._stack: list[str] = []

    def _qual(self, name: str) -> str:
        return ".".join(self._stack + [name]) if self._stack else name

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_fn(self, node):
        self._stack.append(self._qual(node.name))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Assign(self, node):
        # g = jax.jit(f) / g = jax.vmap(f): f joins the traced set, g
        # becomes a known jitted callable name
        if isinstance(node.value, ast.Call):
            chain = _attr_chain(node.value.func)
            if _is_jit_name(chain) or _is_vmap_name(chain):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.d.jitted_names.add(t.id)
                for a in node.value.args:
                    inner = _attr_chain(a)
                    if inner:
                        self._mark_root(inner)
        self.generic_visit(node)

    def _mark_root(self, name: str) -> None:
        for qual in (self._qual(name), name):
            rec = self.d.fns.get(qual)
            if rec is not None:
                rec.is_root = True
                return
        for qual, rec in self.d.fns.items():
            if qual.endswith("." + name):
                rec.is_root = True
                return

    def visit_Call(self, node):
        chain = _attr_chain(node.func)
        if chain and self._stack:
            cur = self.d.fns.get(self._stack[-1])
            if cur is not None:
                # resolve bare names and self.X to local functions
                cands = [self._qual(chain), chain]
                if chain.startswith("self.") and "." not in chain[5:]:
                    # method on the enclosing class, if any
                    parts = self._stack[-1].split(".")
                    if len(parts) >= 2:
                        cands.append(".".join(parts[:-1] + [chain[5:]]))
                    cands.append(chain[5:])
                for c in cands:
                    if c in self.d.fns:
                        cur.calls.add(c)
                        break
        # jit(f) / vmap(f) with a local function argument marks it traced
        if chain and (_is_jit_name(chain) or _is_vmap_name(chain)):
            for a in node.args:
                inner = _attr_chain(a)
                if inner:
                    self._mark_root(inner)
        self.generic_visit(node)


def _collect(mod: Module) -> _DefCollector:
    col = _DefCollector()
    col.visit(mod.tree)
    _CallCollector(col).visit(mod.tree)
    return col


def _traced_set(col: _DefCollector) -> set[str]:
    traced = {q for q, r in col.fns.items() if r.is_root}
    # nested defs inside a traced function body are traced too
    changed = True
    while changed:
        changed = False
        for q, r in col.fns.items():
            if q in traced:
                for callee in r.calls:
                    if callee not in traced:
                        traced.add(callee)
                        changed = True
            elif r.parent in traced:
                traced.add(q)
                changed = True
    return traced


def check(proj: Project):
    for mod in proj.modules:
        yield from _check_module(mod)


def _check_module(mod: Module):
    col = _collect(mod)
    traced = _traced_set(col)

    for qual in sorted(traced):
        rec = col.fns[qual]
        yield from _check_traced_fn(mod, col, rec)

    yield from _check_jit_per_call(mod, col, traced)
    yield from _check_varying_static(mod, col)
    yield from _check_jit_tracked(mod, col, traced)


_PY_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "bytes",
                          "TimeUnit"}


def _py_scalar_params(fn: ast.FunctionDef) -> set[str]:
    """Params annotated as plain Python scalars are trace-time constants
    (static-by-convention), not traced arrays."""
    out: set[str] = set()
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        ann = a.annotation
        if ann is None:
            continue
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                continue
        chain = _attr_chain(ann)
        if chain and chain.rsplit(".", 1)[-1] in _PY_SCALAR_ANNOTATIONS:
            out.add(a.arg)
    return out


def _check_traced_fn(mod: Module, col: _DefCollector, rec: _FnRec):
    fn = rec.node
    params = {a.arg for a in fn.args.posonlyargs + fn.args.args
              + fn.args.kwonlyargs} - rec.statics - {"self", "cls"} \
        - _py_scalar_params(fn)
    own_defs = {f.name for f in ast.walk(fn)
                if isinstance(f, ast.FunctionDef) and f is not fn}

    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            yield Finding(
                "jax-global-mutation", mod.path, node.lineno,
                f"traced function {rec.qual} declares "
                f"global {', '.join(node.names)} — the write happens once "
                f"at trace time, then never again for cached executables")
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None:
            continue
        # impure host calls
        leaf_owner = chain.rsplit(".", 1)[0] if "." in chain else ""
        if chain in _IMPURE_CALLS or any(
                leaf_owner == o or leaf_owner.startswith(o + ".")
                for o in _IMPURE_OWNERS):
            yield Finding(
                "jax-impure-call", mod.path, node.lineno,
                f"traced function {rec.qual} calls {chain}() — evaluated "
                f"once at trace time and constant-folded into every cached "
                f"executable")
            continue
        # module-level container mutation
        if "." in chain:
            owner, attr = chain.rsplit(".", 1)
            if attr in _MUTATORS and owner in col.module_names and \
                    owner not in params and owner not in own_defs:
                yield Finding(
                    "jax-global-mutation", mod.path, node.lineno,
                    f"traced function {rec.qual} mutates module-level "
                    f"{owner} via .{attr}() — trace-time side effect, "
                    f"invisible to cached executables")
        # host materialization of traced parameters
        yield from _materialize_hits(mod, rec, node, chain, params)


def _materialize_hits(mod: Module, rec: _FnRec, node: ast.Call,
                      chain: str, params: set[str]):
    def uses_param(expr: ast.AST) -> str | None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in params:
                return sub.id
        return None

    owner = chain.split(".")[0]
    if owner in ("np", "numpy") and not chain.startswith("np.random"):
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            p = uses_param(a)
            if p is not None:
                yield Finding(
                    "jax-host-materialize", mod.path, node.lineno,
                    f"traced function {rec.qual} passes traced parameter "
                    f"'{p}' to {chain}() — numpy forces host "
                    f"materialization (ConcretizationTypeError under jit)")
                return
    if chain in ("float", "int", "bool") and node.args:
        p = uses_param(node.args[0])
        if p is not None:
            yield Finding(
                "jax-host-materialize", mod.path, node.lineno,
                f"traced function {rec.qual} calls {chain}() on traced "
                f"parameter '{p}' — concretizes the tracer")
    if chain.endswith(".item") and chain.split(".")[0] in params:
        yield Finding(
            "jax-host-materialize", mod.path, node.lineno,
            f"traced function {rec.qual} calls .item() on traced "
            f"parameter '{chain.split('.')[0]}'")


def _enclosing_cached(rec: _FnRec, col: _DefCollector) -> bool:
    for dec in rec.node.decorator_list:
        chain = _attr_chain(dec) or (
            _attr_chain(dec.func) if isinstance(dec, ast.Call) else None)
        if chain and chain.rsplit(".", 1)[-1] in ("lru_cache", "cache",
                                                  "cached_property"):
            return True
    return False


def _check_jit_per_call(mod: Module, col: _DefCollector, traced: set[str]):
    """jit/vmap constructed inside an uncached plain function."""
    for qual, rec in col.fns.items():
        if qual in traced or _enclosing_cached(rec, col):
            continue
        fn = rec.node
        # find jit/vmap constructions in THIS function's direct body (not
        # nested defs: those are charged to their own record)
        nested = {id(n) for f in ast.walk(fn)
                  if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and f is not fn for n in ast.walk(f)}
        for node in ast.walk(fn):
            if id(node) in nested or not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if _is_sharding_ctor(chain):
                if _is_cached_store(mod, node):
                    continue
                yield Finding(
                    "jax-jit-per-call", mod.path, node.lineno,
                    f"{qual} constructs {chain}(...) per call with no "
                    f"cache — a per-eval mesh/sharding object defeats "
                    f"jit's dispatch fast path and can mint fresh "
                    f"executable cache keys (build it once in an "
                    f"lru_cache factory, parallel/mesh.py style)")
                continue
            if not (_is_jit_name(chain) or _is_vmap_name(chain)):
                continue
            if _is_cached_store(mod, node):
                continue
            yield Finding(
                "jax-jit-per-call", mod.path, node.lineno,
                f"{qual} constructs {chain}(...) per call with no cache — "
                f"every invocation re-traces and re-compiles (wrap the "
                f"factory in functools.lru_cache or store in a keyed cache)")


def _is_cached_store(mod: Module, call: ast.Call) -> bool:
    """True when the jit(...) result is stored into a subscripted cache or
    passed to .setdefault(...) — the keyed-cache idioms."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and node.value is call:
            return any(isinstance(t, ast.Subscript) for t in node.targets)
        if isinstance(node, ast.Call) and call in node.args and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "setdefault":
            return True
    return False


def _check_varying_static(mod: Module, col: _DefCollector):
    """Jitted call sites inside loops whose args vary shape per iteration."""
    jitted = col.jitted_names

    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        loop_vars: set[str] = set()
        if isinstance(node, ast.For):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    loop_vars.add(t.id)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            chain = _attr_chain(sub.func)
            if chain is None:
                continue
            leaf = chain.rsplit(".", 1)[-1]
            if leaf not in jitted:
                continue
            for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                hit = _varying_shape_expr(a, loop_vars)
                if hit:
                    yield Finding(
                        "jax-varying-static", mod.path, sub.lineno,
                        f"jitted {leaf}() called in a loop with {hit} — "
                        f"each iteration is a fresh shape/static bucket, "
                        f"each bucket a recompile (bucket the shape first, "
                        f"e.g. dispatch.next_pow2 padding)")
                    break


def _varying_shape_expr(expr: ast.AST, loop_vars: set[str]) -> str | None:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Subscript):
            # x[i], x[i:j] with a loop variable in the index
            for n in ast.walk(sub.slice):
                if isinstance(n, ast.Name) and n.id in loop_vars:
                    return f"an argument sliced by loop variable '{n.id}'"
        if isinstance(sub, ast.Call):
            chain = _attr_chain(sub.func)
            if chain == "len":
                for n in ast.walk(sub):
                    if isinstance(n, ast.Name) and n.id in loop_vars:
                        return "a per-iteration len()"
    return None


# ---------------------------------------------------------------------------
# inv-jit-tracked: every fetched program call runs under a jit_tracker
# ---------------------------------------------------------------------------
#
# The serving-path discipline (utils/dispatch): a jitted program fetched
# from a factory (`prog = _program(sig, mesh)` where the factory returns
# `jax.jit(...)`) or built locally (`g = jax.jit(f)`) is EXECUTED inside
# `with dispatch.jit_tracker(op, prog, sig=...)` so the compute plane
# can attribute cache hits/misses, compile time, execute time and
# evictions. Blessed scopes that never flag: the traced set (calls
# during tracing are one program, not dispatches), the factories
# themselves, and the tracker with-block. Module-level decorated kernels
# called by their own host wrappers (encoding/m3tsz/tpu.py style) are
# out of scope — the wrapper IS the tracked unit, one level up.

_TRACKER_CHAINS = ("jit_tracker", "dispatch.jit_tracker")


def _is_tracker_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        _attr_chain(node.func) in _TRACKER_CHAINS


def _own_nodes(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested defs (nested
    functions are separate scopes with their own _FnRec)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _factory_quals(col: _DefCollector) -> set[str]:
    """Functions that RETURN a jitted callable: `return jax.jit(run)` or
    `return kernel` where kernel is a nested jit root."""
    out: set[str] = set()
    for qual, rec in col.fns.items():
        nested_roots = {r.node.name for r in col.fns.values()
                        if r.parent == qual and r.is_root}
        for node in _own_nodes(rec.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Call):
                chain = _attr_chain(v.func)
                if _is_jit_name(chain) or _is_vmap_name(chain):
                    out.add(qual)
                    break
            if isinstance(v, ast.Name) and v.id in nested_roots:
                out.add(qual)
                break
    return out


def _check_jit_tracked(mod: Module, col: _DefCollector, traced: set[str]):
    factories = _factory_quals(col)
    factory_leaves = {q.rsplit(".", 1)[-1] for q in factories}

    def is_factory_chain(chain: str | None) -> bool:
        return chain is not None and \
            chain.rsplit(".", 1)[-1] in factory_leaves

    for qual, rec in col.fns.items():
        if qual in traced or qual in factories:
            continue
        jitted: set[str] = set()      # locals bound to jitted callables
        trackers: set[str] = set()    # locals bound to a jit_tracker

        def bless_names(item_expr: ast.AST) -> bool:
            if _is_tracker_call(item_expr):
                return True
            return isinstance(item_expr, ast.Name) and \
                item_expr.id in trackers

        def visit(node: ast.AST, blessed: bool):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # separate scope, checked on its own _FnRec
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                chain = _attr_chain(node.value.func)
                kind = None
                if _is_jit_name(chain) or _is_vmap_name(chain) or \
                        is_factory_chain(chain):
                    kind = jitted
                elif chain in _TRACKER_CHAINS:
                    kind = trackers
                if kind is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            kind.add(t.id)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = blessed or any(
                    bless_names(item.context_expr) for item in node.items)
                for item in node.items:
                    yield from visit(item.context_expr, blessed)
                for child in node.body:
                    yield from visit(child, inner)
                return
            if isinstance(node, ast.Call) and not blessed:
                chain = _attr_chain(node.func)
                callee = None
                if chain is not None and \
                        chain.rsplit(".", 1)[-1] in jitted:
                    callee = chain
                elif isinstance(node.func, ast.Call) and \
                        is_factory_chain(_attr_chain(node.func.func)):
                    callee = (_attr_chain(node.func.func) or "factory") \
                        + "(...)"
                if callee is not None:
                    yield Finding(
                        "inv-jit-tracked", mod.path, node.lineno,
                        f"{qual} calls jitted program {callee} outside a "
                        f"dispatch.jit_tracker — the compute plane cannot "
                        f"attribute its cache behaviour or device time; "
                        f"wrap the call: `with dispatch.jit_tracker(op, "
                        f"fn, sig=...): fn(...)`")
            for child in ast.iter_child_nodes(node):
                yield from visit(child, blessed)

        for stmt in rec.node.body:
            yield from visit(stmt, False)
