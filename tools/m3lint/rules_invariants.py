"""Project-invariant rules (rule family ``inv-*``).

Absorbs tools/check_observability.py (PR 4-7's five observability
invariants) as rules 1-5 and adds three new ones:

``inv-tracepoint-unique``   tracepoint constants in utils/trace.py unique
``inv-fault-instrumented``  every declared fault point's module carries a
                            metric scope or span at the seam
``inv-exemplar-capture``    the Scope histogram entry points capture
                            exemplars (p99 bucket -> stitched trace link)
``inv-exporter-registered`` every service entrypoint registers the
                            telemetry-exporter drainer
``inv-admission-counted``   tenant admission decisions counted, sheds
                            carry the TENANT_SHED tracepoint
``inv-fault-point-unique``  every fault-point NAME is declared at exactly
                            one code site — two seams sharing a name merge
                            their injection schedules and their stats
                            (deliberate shared seams carry a waiver)
``inv-histogram-catalog``   every literal histogram/timer name is listed
                            in utils/metric_catalog.py — the catalog is
                            what dashboards and the self-scrape contract
                            are written against
``inv-crash-swallow``       no bare/broad ``except`` around a fault seam
                            that would swallow ``SimulatedCrash`` without
                            re-raising or escalating: a swallowed crash
                            turns every chaos assertion into a lie. Seams
                            are found transitively through same-module
                            calls (the peers.py bug class: the broad
                            except wraps an RPC helper whose
                            ``faults.check`` lives one call down)
``inv-wire-frame-scope``    frame codec descriptors (``struct.Struct``,
                            ``np.dtype``) built once at module scope,
                            never per call — a per-request construction
                            re-parses the format string on the hot
                            handler path (the utils/wire.py idiom)

The fixed-project-file rules (tracepoints, exemplars, exporter,
admission) run in whole-tree mode only; the fault-seam, catalog, and
crash-swallow rules are per-module so fixture tests can exercise them.
"""

from __future__ import annotations

import ast
import os

from tools.m3lint.engine import attr_chain as _attr_chain
from tools.m3lint.engine import PKG, Finding, Module, Project

RULES = {
    "inv-tracepoint-unique": "duplicate tracepoint constant",
    "inv-fault-instrumented": "fault point with no observability at its seam",
    "inv-exemplar-capture": "histogram entry point without exemplar capture",
    "inv-exporter-registered": "service entrypoint missing the exporter",
    "inv-admission-counted": "admission decision without counters/tracepoint",
    "inv-fault-point-unique": "fault point name declared at more than one site",
    "inv-histogram-catalog": "histogram/timer name missing from the catalog",
    "inv-crash-swallow": "broad except around a fault seam swallows SimulatedCrash",
    "inv-queue-gauge": "bounded queue/ring without a monitor_queue registration",
    "inv-pagepool-gauge": "page pool/hot tier constructed without a "
                          "saturation-plane registration",
    "inv-wire-frame-scope": "frame codec struct/dtype constructed per call",
}

# modules whose fault-point mentions are documentation or test scaffolding
EXEMPT = {
    os.path.join("utils", "faults.py"),      # the registry itself (docs)
    os.path.join("tools", "race_check.py"),  # stress harness
}

_OBS_ATTRS = {"span", "histogram", "observe", "counter", "timer", "gauge",
              "subscope", "root_scope"}

SERVICE_ENTRYPOINTS = (
    os.path.join("services", "coordinator.py"),
    os.path.join("services", "dbnode.py"),
    os.path.join("services", "aggregator.py"),
    os.path.join("cluster", "kvd.py"),
)

_HISTO_ATTRS = {"observe", "histogram", "histogram_handle", "timer"}


# ---------------------------------------------------------------------------
# shared scanners
# ---------------------------------------------------------------------------

class _SeamScanner(ast.NodeVisitor):
    """Fault points + instrumentation references in one module."""

    def __init__(self):
        self.fault_points: list[tuple[str, int]] = []
        self.instrumented = False

    def visit_Call(self, node: ast.Call):
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if attr in ("check", "torn_write", "wrap_io"):
            owner = getattr(fn, "value", None)
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if owner_name in ("faults", None) or attr == "check":
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str) and "." in arg.value:
                        self.fault_points.append((arg.value, node.lineno))
                        break
        if attr in _OBS_ATTRS:
            self.instrumented = True
        self.generic_visit(node)


def _function_references(tree: ast.AST, func_name: str, needle: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id == needle:
                    return True
                if isinstance(sub, ast.Attribute) and sub.attr == needle:
                    return True
    return False


def _project_tree(proj: Project, path: str) -> ast.AST | None:
    """Tree for a fixed project file — from the engine's already-parsed
    module table when present (whole-tree mode always has it; re-reading
    would also bypass the waiver/parse-error machinery), falling back to
    a direct parse only for paths outside the analyzed set."""
    mod = proj.by_path.get(os.path.abspath(path))
    if mod is not None:
        return mod.tree
    try:
        return ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return None


# ---------------------------------------------------------------------------
# rules 1-5: the absorbed check_observability invariants
# ---------------------------------------------------------------------------

def _check_tracepoints(proj: Project):
    path = os.path.join(PKG, "utils", "trace.py")
    tree = _project_tree(proj, path)
    if tree is None:
        yield Finding("inv-tracepoint-unique", path, 1,
                      "utils/trace.py unreadable")
        return
    seen: dict[str, tuple[str, int]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and not node.targets[0].id.startswith("_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            name, value = node.targets[0].id, node.value.value
            if value in seen:
                prev, _line = seen[value]
                yield Finding(
                    "inv-tracepoint-unique", path, node.lineno,
                    f"tracepoint {name} duplicates {prev} (both {value!r}) "
                    f"— they would silently merge in every trace tree")
            else:
                seen[value] = (name, node.lineno)


def _check_fault_seams(proj: Project):
    """Rules 2 and 6 share the project-wide fault-point catalog."""
    catalog: dict[str, list[tuple[str, int]]] = {}
    for mod in proj.modules:
        if mod.rel in EXEMPT:
            continue
        sc = _SeamScanner()
        sc.visit(mod.tree)
        if not sc.fault_points:
            continue
        for point, lineno in sc.fault_points:
            catalog.setdefault(point, []).append((mod.path, lineno))
        if not sc.instrumented:
            pts = ", ".join(p for p, _ in sc.fault_points)
            yield Finding(
                "inv-fault-instrumented", mod.path, sc.fault_points[0][1],
                f"module declares fault point(s) [{pts}] but has no metric "
                f"scope or trace span at the seam — a seam we can break "
                f"but not see")
    for point, sites in sorted(catalog.items()):
        if len(sites) <= 1:
            continue
        first_path, first_line = sites[0]
        for path, line in sites[1:]:
            yield Finding(
                "inv-fault-point-unique", path, line,
                f"fault point {point!r} already declared at "
                f"{os.path.relpath(first_path, PKG)}:{first_line} — two "
                f"seams sharing a name merge their injection schedules "
                f"and stats (waive if the paths are one semantic seam)")


def _check_exemplar_capture(proj: Project):
    path = os.path.join(PKG, "utils", "instrument.py")
    tree = _project_tree(proj, path)
    if tree is None:
        yield Finding("inv-exemplar-capture", path, 1,
                      "utils/instrument.py unreadable")
        return
    if not _function_references(tree, "observe", "_active_exemplar_trace") \
            and not _function_references(tree, "observe", "_exemplar"):
        yield Finding(
            "inv-exemplar-capture", path, 1,
            "Scope.observe does not capture exemplars — seam histograms "
            "lose the p99-bucket -> trace link")
    if not (_function_references(tree, "histogram_handle",
                                 "_active_exemplar_trace")
            or _function_references(tree, "histogram_handle", "exemplars")):
        yield Finding(
            "inv-exemplar-capture", path, 1,
            "histogram_handle's hot-path closure does not capture exemplars")
    if not _function_references(tree, "observe_locked", "exemplars"):
        yield Finding(
            "inv-exemplar-capture", path, 1,
            "_Histogram.observe_locked has no exemplar storage")


def _check_exporter_registered(proj: Project):
    for rel in SERVICE_ENTRYPOINTS:
        path = os.path.join(PKG, rel)
        tree = _project_tree(proj, path)
        if tree is None:
            yield Finding("inv-exporter-registered", path, 1,
                          f"{rel}: unreadable/unparseable")
            continue
        found = any(isinstance(n, ast.Name) and n.id == "exporter_from_config"
                    for n in ast.walk(tree))
        if not found:
            yield Finding(
                "inv-exporter-registered", path, 1,
                f"service entrypoint {rel} does not register the telemetry "
                f"exporter (exporter_from_config) — a process outside the "
                f"export plane is a blind spot")


def _check_admission(proj: Project):
    path = os.path.join(PKG, "utils", "tenantlimits.py")
    tree = _project_tree(proj, path)
    if tree is None:
        yield Finding("inv-admission-counted", path, 1,
                      "utils/tenantlimits.py unreadable")
        return
    for fn in ("admit_write", "admit_query"):
        counted = (_function_references(tree, fn, "_allow")
                   and _function_references(tree, fn, "_shed")) \
            or _function_references(tree, fn, "counter")
        if not counted:
            yield Finding(
                "inv-admission-counted", path, 1,
                f"decision point {fn} does not emit per-tenant allow/shed "
                f"counters")
    if not _function_references(tree, "_shed", "counter"):
        yield Finding("inv-admission-counted", path, 1,
                      "the shed path does not emit a per-tenant counter")
    if not (_function_references(tree, "_shed", "span")
            and _function_references(tree, "_shed", "TENANT_SHED")):
        yield Finding("inv-admission-counted", path, 1,
                      "the shed path does not carry the TENANT_SHED "
                      "tracepoint")


# ---------------------------------------------------------------------------
# rule 7: histogram catalog
# ---------------------------------------------------------------------------

def _load_catalog(proj: Project) -> set[str] | None:
    path = os.path.join(PKG, "utils", "metric_catalog.py")
    tree = _project_tree(proj, path)
    if tree is None:
        return None
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id in ("HISTOGRAMS", "TIMERS"):
            try:
                val = ast.literal_eval(node.value)
            except ValueError:
                continue
            names.update(val)
    return names


def _check_histogram_catalog(proj: Project):
    catalog = _load_catalog(proj)
    if catalog is None:
        cat_path = os.path.join(PKG, "utils", "metric_catalog.py")
        yield Finding("inv-histogram-catalog", cat_path, 1,
                      "utils/metric_catalog.py missing or unparseable — "
                      "the histogram catalog is the exposition contract")
        return
    for mod in proj.modules:
        if mod.rel == os.path.join("utils", "metric_catalog.py"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in _HISTO_ATTRS:
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if name not in catalog:
                yield Finding(
                    "inv-histogram-catalog", mod.path, node.lineno,
                    f"histogram/timer name {name!r} is not in "
                    f"utils/metric_catalog.py — add it to the catalog so "
                    f"dashboards and the self-scrape contract see it")


# ---------------------------------------------------------------------------
# rule 8: SimulatedCrash-swallowing excepts
# ---------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        chain = _attr_chain(t)
        if chain and chain.rsplit(".", 1)[-1] in _BROAD:
            return True
    return False


def _mentions_crash(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "SimulatedCrash":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "SimulatedCrash", "escalate"):
            return True
        if isinstance(sub, ast.Name) and sub.id == "escalate":
            return True
    return False


def _call_name(call: ast.Call) -> str | None:
    fn = call.func
    return fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)


def _is_direct_seam(call: ast.Call) -> bool:
    attr = _call_name(call)
    if attr not in ("check", "torn_write", "wrap_io"):
        return False
    owner = getattr(call.func, "value", None)
    if isinstance(owner, ast.Name) and owner.id == "faults":
        return True
    return attr in ("torn_write", "wrap_io")


# object-protocol names too generic to resolve by name: `q.get()`,
# `event.set()`, `channel.close()` would otherwise match any same-module
# seam-bearing `def get/set/close` (a queue is not a KV server). Calls to
# these are never chased; the direct-seam check still covers their
# bodies where it matters.
_GENERIC_NAMES = frozenset({
    "get", "set", "put", "pop", "close", "open", "read", "write", "flush",
    "send", "recv", "start", "stop", "run", "join", "wait", "clear", "add",
    "append", "update", "remove", "discard", "items", "keys", "values",
    "copy", "encode", "decode", "acquire", "release", "submit", "result",
    "cancel", "done", "next",
})


def _body_has_seam(stmts: list[ast.stmt],
                   seam_names: frozenset[str] = frozenset()) -> bool:
    """True when the statements reach a fault seam — directly
    (``faults.check``/``torn_write``/``wrap_io``) or through a call to a
    same-module callable whose body reaches one (``seam_names``, from
    `_seam_bearing_names`)."""
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                if _is_direct_seam(sub):
                    return True
                if _call_name(sub) in seam_names:
                    return True
    return False


def _seam_bearing_names(mod: Module) -> frozenset[str]:
    """Names of this module's functions/methods whose bodies reach a
    fault seam, transitively through same-module calls (fixpoint — the
    concurrency family's intra-module call chasing, applied to crash
    propagation). Matching is by terminal name, so ``peer.block_starts()``
    resolves to any same-module ``def block_starts`` — the cross-function
    bug class where ``except Exception`` wraps an RPC helper whose seam
    lives one call down (storage/peers.py's bootstrap/metadata/stream
    loops around the ``peer.http`` seam)."""
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name not in _GENERIC_NAMES:
            defs.setdefault(node.name, []).append(node)
    seam: set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fns in defs.items():
            if name in seam:
                continue
            for fn in fns:
                if _body_has_seam(fn.body, frozenset(seam)):
                    seam.add(name)
                    changed = True
                    break
    return frozenset(seam)


def _check_crash_swallow(proj: Project):
    for mod in proj.modules:
        if mod.rel in EXEMPT:
            continue
        seam_names = _seam_bearing_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _body_has_seam(node.body, seam_names):
                continue
            crash_handled_earlier = False
            for h in node.handlers:
                if not _handler_is_broad(h):
                    if h.type is not None and _mentions_crash(h.type):
                        crash_handled_earlier = True
                    continue
                if crash_handled_earlier:
                    break
                reraises = any(isinstance(s, ast.Raise)
                               for s in ast.walk(ast.Module(
                                   body=h.body, type_ignores=[])))
                if reraises or _mentions_crash(ast.Module(
                        body=h.body, type_ignores=[])):
                    break
                label = "bare except:" if h.type is None else \
                    f"except {ast.unparse(h.type)}:"
                yield Finding(
                    "inv-crash-swallow", mod.path, h.lineno,
                    f"{label} around a fault seam swallows SimulatedCrash "
                    f"— re-raise it, call faults.escalate(e), or catch "
                    f"SimulatedCrash explicitly first (a swallowed crash "
                    f"falsifies every chaos assertion downstream)")
                break


# ---------------------------------------------------------------------------
# rule 9: bounded queues must register with the saturation plane
# ---------------------------------------------------------------------------

def _unbounding_const(node: ast.AST) -> bool:
    """A literal that makes the buffer unbounded (None maxlen, 0/negative
    maxsize)."""
    return isinstance(node, ast.Constant) and (
        node.value is None
        or (isinstance(node.value, (int, float)) and node.value <= 0))


def _is_bounded_queue_ctor(call: ast.Call) -> bool:
    """A ``deque(..., maxlen)`` (non-None) or ``queue.Queue(maxsize)``
    construction, keyword OR positional — a bounded buffer that can
    silently fill and drop."""
    name = _call_name(call)
    if name == "deque":
        for kw in call.keywords:
            if kw.arg == "maxlen":
                return not _unbounding_const(kw.value)
        return len(call.args) >= 2 and not _unbounding_const(call.args[1])
    if name == "Queue":
        for kw in call.keywords:
            if kw.arg == "maxsize":
                return not _unbounding_const(kw.value)
        return len(call.args) >= 1 and not _unbounding_const(call.args[0])
    return False


# memory-pool ctors held to the same registration discipline as bounded
# queues (ISSUE 15): a page pool or hot tier that fills/evicts with no
# occupancy gauges is the same invisible-saturation failure mode
_POOL_CTORS = {"PagePool", "HotTier"}
_POOL_REGISTERS = {"monitor_pool", "monitor_queue"}


class _QueueScanner(ast.NodeVisitor):
    """Bounded-queue + pool ctors and their registrations, per enclosing
    class.

    Scope key is the innermost ClassDef (None = module level): a class
    that builds bounded buffers must register at least one monitor; a
    module-level ring is satisfied by a module-level registration (the
    default-instance idiom, e.g. the tracer's span ring)."""

    def __init__(self):
        self._stack: list[ast.ClassDef | None] = [None]
        self.ctors: list[tuple[ast.ClassDef | None, int]] = []
        self.pool_ctors: list[tuple[ast.ClassDef | None, int]] = []
        self.monitored: set[ast.ClassDef | None] = set()
        self.pool_monitored: set[ast.ClassDef | None] = set()

    def visit_ClassDef(self, node: ast.ClassDef):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name == "monitor_queue":
            self.monitored.add(self._stack[-1])
        if name in _POOL_REGISTERS:
            self.pool_monitored.add(self._stack[-1])
        if name in _POOL_CTORS \
                and self._stack[-1] is not None \
                and self._stack[-1].name != name:
            # the class DEFINING the pool is not a construction site
            self.pool_ctors.append((self._stack[-1], node.lineno))
        elif name in _POOL_CTORS and self._stack[-1] is None:
            self.pool_ctors.append((None, node.lineno))
        elif _is_bounded_queue_ctor(node):
            self.ctors.append((self._stack[-1], node.lineno))
        self.generic_visit(node)


def _check_queue_gauges(proj: Project):
    """Every bounded queue/ring must be registered with
    ``instrument.monitor_queue`` so its depth/capacity/drop gauges ride
    the saturation plane (a bounded queue with no gauge fills and drops
    invisibly — the failure mode this PR exists to kill). Deliberately
    unmonitored internals carry a same-line/line-above
    ``# m3lint: disable=inv-queue-gauge`` waiver."""
    for mod in proj.modules:
        if mod.rel in EXEMPT:
            continue
        sc = _QueueScanner()
        sc.visit(mod.tree)
        for cls, lineno in sc.pool_ctors:
            # pool discipline (inv-pagepool-gauge): a PagePool/HotTier
            # construction site must register it on the saturation plane
            # (monitor_pool / monitor_queue) in the SAME scope
            if cls in sc.pool_monitored:
                continue
            yield Finding(
                "inv-pagepool-gauge", mod.path, lineno,
                "page pool / hot tier constructed without a "
                "monitor_pool/monitor_queue registration in this scope "
                "— its occupancy and evictions are invisible to the "
                "saturation plane")
        if not sc.ctors:
            continue
        for cls, lineno in sc.ctors:
            # scope-matched blessing: a class's queues need a monitor in
            # THAT class; module-level rings need a module-level call —
            # one module-level registration must not silence every class
            # in the file
            if cls in sc.monitored:
                continue
            yield Finding(
                "inv-queue-gauge", mod.path, lineno,
                "bounded queue/ring is not registered with "
                "instrument.monitor_queue — it can saturate and drop "
                "with no depth/capacity/drop gauges on the saturation "
                "plane (waive only for intentionally unmonitored "
                "internals)")


# ---------------------------------------------------------------------------
# rule: frame codec objects built once at module scope
# ---------------------------------------------------------------------------

# constructor chains that COMPILE a wire-format descriptor: each call
# parses a format string / field spec, so one per request on a hot
# handler is pure re-parse overhead (the utils/wire.py + peers.py
# ROLLUP_DTYPE idiom is module scope, once per process). struct.pack /
# struct.unpack with a literal format are fine — the struct module
# caches compiled formats internally.
_FRAME_CTORS = {"struct.Struct", "np.dtype", "numpy.dtype"}


def _check_wire_frame_scope(proj: Project):
    """inv-wire-frame-scope: a ``struct.Struct(...)`` / ``np.dtype(...)``
    constructed inside a function or method body — frame descriptors
    belong at module scope (built once), not per call on a request
    handler. Waive for genuinely dynamic descriptors (a dtype computed
    from runtime shape)."""
    for mod in proj.modules:
        seen: set[int] = set()  # nested defs re-walk inner calls
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or node.lineno in seen:
                    continue
                chain = _attr_chain(node.func)
                if chain not in _FRAME_CTORS:
                    continue
                seen.add(node.lineno)
                yield Finding(
                    "inv-wire-frame-scope", mod.path, node.lineno,
                    f"{chain}(...) constructed inside {fn.name}() — frame "
                    f"codec descriptors are parsed at construction; build "
                    f"them ONCE at module scope (the utils/wire.py / "
                    f"peers.ROLLUP_DTYPE idiom), not per call")


def check(proj: Project):
    # per-module rules run in both whole-tree and explicit-paths mode
    yield from _check_fault_seams(proj)
    yield from _check_histogram_catalog(proj)
    yield from _check_crash_swallow(proj)
    yield from _check_queue_gauges(proj)
    yield from _check_wire_frame_scope(proj)
    if not proj.whole_tree:
        return
    # project-level rules reference fixed files; whole-tree mode only
    yield from _check_tracepoints(proj)
    yield from _check_exemplar_capture(proj)
    yield from _check_exporter_registered(proj)
    yield from _check_admission(proj)
