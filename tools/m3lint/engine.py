"""m3lint engine: module loading, waiver bookkeeping, rule dispatch, CLI.

The engine is deliberately import-light (stdlib ``ast`` only): it must run
before every test lane in well under the ~10s budget, and it must never
import m3_tpu itself (which would pull in jax and, with the axon tunnel
down, could wedge the interpreter before a single test runs).
"""

from __future__ import annotations

import argparse
import ast
import io
import os
import re
import sys
import time
import tokenize
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PKG = os.path.join(REPO, "m3_tpu")

_WAIVER_RE = re.compile(r"#\s*m3lint:\s*disable=([a-z0-9,\-\s]+)")


def attr_chain(node: ast.AST) -> str | None:
    """Dotted name for Name/Attribute chains ('self._lock', 'os.path.x').

    The one name-resolution primitive every rule family shares — it lives
    here so a refinement applies to all of them at once."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # absolute path
    line: int
    message: str

    def render(self) -> str:
        rel = os.path.relpath(self.path, REPO)
        return f"{rel}:{self.line}: {self.rule} {self.message}"


@dataclass
class Waiver:
    line: int           # line the comment sits on
    rules: tuple[str, ...]
    own_line: bool      # comment-only line -> applies to the NEXT line
    used: set = field(default_factory=set)  # rules it actually suppressed


class Module:
    """One parsed source file plus its waiver table."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        with open(self.path, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.path)
        self.waivers: list[Waiver] = []
        # waivers come from COMMENT tokens only — a docstring QUOTING the
        # syntax (this feature gets documented) must not register as a
        # waiver and then fail the gate as lint-unused-waiver. The
        # "m3lint:" pre-filter keeps the tokenize pass off the 100+
        # files that have no waivers at all.
        if "m3lint:" in self.source:
            try:
                toks = list(tokenize.generate_tokens(
                    io.StringIO(self.source).readline))
            except (tokenize.TokenError, IndentationError):
                toks = []  # ast.parse succeeded, so this never fires
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _WAIVER_RE.search(tok.string)
                if not m:
                    continue
                rules = tuple(r.strip() for r in m.group(1).split(",")
                              if r.strip())
                line = tok.start[0]
                own = self.lines[line - 1][: tok.start[1]].strip() == ""
                self.waivers.append(
                    Waiver(line=line, rules=rules, own_line=own))

    @property
    def rel(self) -> str:
        return os.path.relpath(self.path, PKG)

    def waiver_for(self, rule: str, line: int) -> Waiver | None:
        """A waiver covers its own line; a comment-only waiver covers the
        next line instead (the conventional place above a `with` or call)."""
        for w in self.waivers:
            if rule not in w.rules:
                continue
            target = w.line + 1 if w.own_line else w.line
            if target == line:
                return w
        return None


class Project:
    """The set of modules under analysis plus repo-level context."""

    def __init__(self, modules: list[Module], whole_tree: bool):
        self.modules = modules
        self.whole_tree = whole_tree  # project-level invariants only then
        self.by_path = {m.path: m for m in modules}
        self.parse_failures: list[Finding] = []


def _walk_package() -> list[str]:
    paths = []
    for dirpath, dirs, files in os.walk(PKG):
        # sorted so module order (and e.g. which duplicate fault-point
        # site counts as "first declared") is machine-independent
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                paths.append(os.path.join(dirpath, fname))
    return paths


def load_project(paths: list[str] | None = None) -> Project:
    whole_tree = paths is None
    file_paths = _walk_package() if whole_tree else list(paths)
    modules: list[Module] = []
    failures: list[Finding] = []
    for p in file_paths:
        try:
            modules.append(Module(p))
        except (OSError, SyntaxError) as e:
            failures.append(Finding(
                rule="lint-parse-error", path=os.path.abspath(p),
                line=getattr(e, "lineno", 1) or 1,
                message=f"unreadable/unparseable: {e}"))
    proj = Project(modules, whole_tree=whole_tree)
    proj.parse_failures = failures
    return proj


def _checkers():
    # imported lazily so `python -m tools.m3lint --list-rules` never pays
    # for a rule module with a syntax error twice
    from tools.m3lint import rules_concurrency, rules_invariants, rules_jax

    return (
        rules_concurrency.check,
        rules_jax.check,
        rules_invariants.check,
    )


def all_rules() -> dict[str, str]:
    from tools.m3lint import rules_concurrency, rules_invariants, rules_jax

    out: dict[str, str] = {
        "lint-parse-error": "a linted file failed to parse",
        "lint-unused-waiver": "a waiver comment that suppresses nothing",
    }
    for mod in (rules_concurrency, rules_jax, rules_invariants):
        out.update(mod.RULES)
    return out


def lint_project(proj: Project, select: tuple[str, ...] = ()) -> list[Finding]:
    """Run every checker; apply waivers; flag stale waivers.

    ``select`` restricts to findings whose rule id starts with one of the
    given prefixes (waiver accounting is then restricted the same way, so
    fixture tests can exercise one family at a time).
    """
    raw: list[Finding] = list(proj.parse_failures)
    for check in _checkers():
        raw.extend(check(proj))
    if select:
        raw = [f for f in raw if f.rule.startswith(select)]

    surviving: list[Finding] = []
    for f in raw:
        mod = proj.by_path.get(f.path)
        w = mod.waiver_for(f.rule, f.line) if mod is not None else None
        if w is not None:
            w.used.add(f.rule)
        else:
            surviving.append(f)

    # a waiver nothing hides behind is itself a finding: the enforced
    # baseline must stay exactly as strong as the code claims it is
    for mod in proj.modules:
        for w in mod.waivers:
            for rule in w.rules:
                if select and not rule.startswith(select):
                    continue
                if rule not in w.used:
                    surviving.append(Finding(
                        rule="lint-unused-waiver", path=mod.path, line=w.line,
                        message=f"waiver for {rule} suppresses nothing — "
                                f"delete it (or the fix regressed)"))
    surviving.sort(key=lambda f: (f.path, f.line, f.rule))
    return surviving


def lint_paths(paths: list[str], select: tuple[str, ...] = ()) -> list[Finding]:
    """Lint explicit files (fixture tests use this)."""
    return lint_project(load_project(paths), select=select)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.m3lint",
        description="m3_tpu static analysis (lock discipline, jax purity, "
                    "project invariants)")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole m3_tpu package "
                         "plus project-level invariants)")
    ap.add_argument("--select", default="",
                    help="comma-separated rule-id prefixes to run "
                         "(e.g. 'lock-,jax-')")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(all_rules().items()):
            print(f"{rule:24s} {desc}")
        return 0

    select = tuple(s.strip() for s in args.select.split(",") if s.strip())
    t0 = time.perf_counter()
    proj = load_project(args.paths or None)
    findings = lint_project(proj, select=select)
    dt = time.perf_counter() - t0
    if findings:
        print("m3lint: FAILED", file=sys.stderr)
        for f in findings:
            print(f"  {f.render()}", file=sys.stderr)
        print(f"m3lint: {len(findings)} finding(s) in {len(proj.modules)} "
              f"modules ({dt:.2f}s)", file=sys.stderr)
        return 1
    waived = sum(len(w.used) for m in proj.modules for w in m.waivers)
    print(f"m3lint: OK — {len(proj.modules)} modules clean "
          f"({waived} explicit waivers) in {dt:.2f}s")
    return 0
