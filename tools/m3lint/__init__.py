"""m3lint — project-wide static analysis for the m3_tpu codebase.

Three rule families, all pure-AST (no m3_tpu import, no jax init, fast
enough to run before every test lane):

* ``lock-*``      concurrency discipline: per-module lock-acquisition
                  graphs, lock-order inversions, blocking calls made
                  while holding a lock, unguarded mutation of
                  lock-guarded attributes.
* ``jax-*``       jit-purity and recompile hazards inside functions
                  reachable from ``jax.jit``/``vmap`` call sites.
* ``inv-*``       project invariants (absorbs tools/check_observability):
                  tracepoint uniqueness, fault-seam observability,
                  exemplar capture, exporter registration, admission
                  counters — plus fault-point uniqueness, the histogram
                  catalog, and SimulatedCrash-swallowing excepts.

Findings are ``path:line: rule-id message``.  Suppressions are explicit
in-code waivers::

    something_flagged()  # m3lint: disable=lock-blocking-call

or, on their own line, applying to the next line::

    # m3lint: disable=lock-order
    with self._lock_b:

Every waiver must suppress a live finding — stale waivers are themselves
findings (``lint-unused-waiver``), so the enforced baseline can only be
relaxed visibly, in code, under review.
"""

from tools.m3lint.engine import (  # noqa: F401
    Finding,
    Module,
    Project,
    lint_paths,
    lint_project,
    main,
)
