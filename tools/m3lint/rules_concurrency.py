"""Concurrency-discipline rules (rule family ``lock-*``).

Per module, the checker builds a lock-acquisition model:

* lock identities — ``self.X = threading.Lock()/RLock()/Condition()``
  assigned in a class body/method gives lock ``Class.X``; module-level
  ``X = threading.Lock()`` gives ``module.X``.  A ``with`` on an
  attribute whose name *looks* like a lock (``_lock``, ``_mu`` …) but has
  no local definition is still tracked (conservatively, reentrancy
  unknown) so cross-class handles don't go invisible.
* per-function summaries — which locks a function (transitively, through
  intra-module calls) acquires, and which blocking primitives it
  (transitively) reaches.  Computed to a fixpoint so helper indirection
  doesn't hide an edge.
* an order graph — edge A→B each time B is acquired (directly or through
  a call) while A is held.  A→B with B⇝A reachable is a lock-order
  inversion: two threads entering from the two ends deadlock.

Three rules:

``lock-order``            inversion edges (incl. re-acquiring a known
                          non-reentrant lock while already held)
``lock-blocking-call``    socket I/O, fsync, subprocess, HTTP, sleeps and
                          thread joins executed while holding a lock
``lock-guarded-mutation`` an attribute mutated under a class's lock in
                          one method but mutated with no lock held in
                          another — the guard is decoration, not
                          discipline

The model is intra-module and intra-class by design: cross-module lock
graphs would need whole-program aliasing and drown the signal in noise.
The runtime shadow-lock checker (utils/lockcheck, ``M3_TPU_LOCK_CHECK=1``)
covers the dynamic, cross-module residue.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from tools.m3lint.engine import Finding, Module, Project
from tools.m3lint.engine import attr_chain as _attr_chain

RULES = {
    "lock-order": "lock-order inversion (potential deadlock)",
    "lock-blocking-call": "blocking call while holding a lock",
    "lock-guarded-mutation": "lock-guarded attribute mutated without the lock",
    "conc-handrolled-pipeline":
        "hand-rolled thread-pool/queue pipeline outside the executor seam",
}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock", "Condition"}
_LOCKISH_NAME = re.compile(r"(^|_)(lock|mu|mutex)$")

# blocking primitives: (owner constraint, attr/name). owner None = any.
_BLOCKING_ATTRS = {
    # sockets / network
    "connect": None, "accept": None, "recv": None, "recvfrom": None,
    "recv_into": None, "sendall": None, "create_connection": "socket",
    "getaddrinfo": None, "makefile": None,
    # HTTP
    "urlopen": None, "getresponse": None,
    # subprocess
    "run": "subprocess", "Popen": "subprocess", "check_call": "subprocess",
    "check_output": "subprocess", "call": "subprocess", "communicate": None,
    # durability / scheduling
    "fsync": None, "sleep": None, "wait": None,
}
# `.join` blocks only on threads/processes; str.join is everywhere, so the
# owner name must look thread-like before it counts
_JOINISH_OWNER = re.compile(r"(thread|worker|proc|child)", re.IGNORECASE)


@dataclass
class LockDef:
    lock_id: str      # "Class.attr" or "module.name"
    reentrant: bool
    line: int


@dataclass
class FuncInfo:
    qualname: str                 # "Class.method" or "func"
    node: ast.FunctionDef
    cls: str | None
    # transitive summaries (fixpoint-computed)
    acquires: set = field(default_factory=set)
    blocking: dict = field(default_factory=dict)   # prim -> via-chain str


class _ModuleModel:
    """Locks, functions and the intra-module call graph of one file."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.locks: dict[str, LockDef] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.mod_name = os.path.splitext(os.path.basename(mod.path))[0]
        self.module_level_names: set[str] = set()
        # Condition(self._lock) wraps a lock: cond.wait() RELEASES it, so
        # the classic `with self._lock: ... self._cond.wait()` idiom is
        # not blocking-while-holding
        self.cond_of: dict[str, str] = {}
        self._collect()

    # -- collection --------------------------------------------------------
    def _collect(self) -> None:
        tree = self.mod.tree
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_level_names.add(t.id)
                        ctor = self._lock_ctor(node.value)
                        if ctor:
                            self.locks[f"{self.mod_name}.{t.id}"] = LockDef(
                                f"{self.mod_name}.{t.id}",
                                ctor in _REENTRANT_CTORS, node.lineno)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_level_names.add(node.name)
                self.funcs[node.name] = FuncInfo(node.name, node, None)
            elif isinstance(node, ast.ClassDef):
                self.module_level_names.add(node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        q = f"{node.name}.{item.name}"
                        self.funcs[q] = FuncInfo(q, item, node.name)
                # self.X = Lock() anywhere in the class's methods
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        ctor = self._lock_ctor(sub.value)
                        if not ctor:
                            continue
                        for t in sub.targets:
                            chain = _attr_chain(t)
                            if chain and chain.startswith("self."):
                                attr = chain[len("self."):]
                                lid = f"{node.name}.{attr}"
                                self.locks[lid] = LockDef(
                                    lid, ctor in _REENTRANT_CTORS,
                                    sub.lineno)
                                if ctor == "Condition" and \
                                        isinstance(sub.value, ast.Call) and \
                                        sub.value.args:
                                    wrapped = _attr_chain(sub.value.args[0])
                                    if wrapped and wrapped.startswith("self."):
                                        self.cond_of[lid] = (
                                            f"{node.name}."
                                            f"{wrapped[len('self.'):]}")

    @staticmethod
    def _lock_ctor(value: ast.AST) -> str | None:
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func)
            if chain:
                leaf = chain.rsplit(".", 1)[-1]
                if leaf in _LOCK_CTORS:
                    return leaf
        return None

    # -- lock identity for a `with` item ----------------------------------
    def lock_id_for(self, expr: ast.AST, cls: str | None) -> str | None:
        chain = _attr_chain(expr)
        if chain is None:
            return None
        if chain.startswith("self.") and cls is not None:
            attr = chain[len("self."):]
            if "." in attr:
                return None  # self.foo.lock — foreign object, skip
            lid = f"{cls}.{attr}"
            if lid in self.locks or _LOCKISH_NAME.search(attr):
                return lid
            return None
        if "." not in chain:
            lid = f"{self.mod_name}.{chain}"
            if lid in self.locks:
                return lid
            if _LOCKISH_NAME.search(chain):
                return lid
        return None

    def is_reentrant(self, lock_id: str) -> bool | None:
        d = self.locks.get(lock_id)
        return d.reentrant if d is not None else None

    # -- call resolution ---------------------------------------------------
    def resolve_call(self, call: ast.Call, cls: str | None) -> str | None:
        chain = _attr_chain(call.func)
        if chain is None:
            return None
        if chain.startswith("self.") and cls is not None:
            name = chain[len("self."):]
            if "." not in name and f"{cls}.{name}" in self.funcs:
                return f"{cls}.{name}"
            return None
        if "." not in chain and chain in self.funcs:
            return chain
        return None


def _blocking_prim(call: ast.Call) -> str | None:
    """Name of the blocking primitive this call is, or None."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        attr = fn.attr
        owner = _attr_chain(fn.value)
        if attr == "join":
            if owner and _JOINISH_OWNER.search(owner):
                return f"{owner}.join"
            return None
        if attr in _BLOCKING_ATTRS:
            need_owner = _BLOCKING_ATTRS[attr]
            if need_owner is None or (owner or "").split(".")[-1] == need_owner \
                    or (owner or "") == need_owner:
                return f"{owner}.{attr}" if owner else attr
        if owner == "requests" and attr in ("get", "post", "put", "delete",
                                            "head", "request"):
            return f"requests.{attr}"
    elif isinstance(fn, ast.Name):
        if fn.id in ("urlopen", "fsync", "create_connection", "getaddrinfo"):
            return fn.id
    return None


class _FuncWalker:
    """Walks one function body tracking the held-lock stack; records
    acquisition edges, blocking hits, attr mutations and call sites."""

    def __init__(self, model: _ModuleModel, info: FuncInfo):
        self.model = model
        self.info = info
        self.edges: list[tuple[str, str, int, str]] = []  # (A, B, line, via)
        self.direct_acquires: set[str] = set()
        self.direct_blocking: list[tuple[str, int, bool]] = []  # (prim, line, held)
        self.calls: list[tuple[str, int, tuple[str, ...]]] = []  # (callee, line, held-stack)
        self.mutations: list[tuple[str, int, bool]] = []  # (attr, line, held)
        self.self_acquire_lines: dict[str, int] = {}

    def walk(self) -> None:
        for stmt in self.info.node.body:
            self._visit(stmt, held=())

    def _visit(self, node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested callables run later, not at this program point
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                lid = self.model.lock_id_for(item.context_expr,
                                             self.info.cls)
                if lid is not None:
                    for h in new_held:
                        self.edges.append((h, lid, node.lineno, ""))
                    self.direct_acquires.add(lid)
                    self.self_acquire_lines.setdefault(lid, node.lineno)
                    new_held = new_held + (lid,)
                else:
                    # later items in `with self._lock, expr():` evaluate
                    # AFTER the earlier locks are taken — visit with the
                    # accumulated held set, not the entry set
                    self._visit(item.context_expr, new_held)
            for stmt in node.body:
                self._visit(stmt, new_held)
            return
        if isinstance(node, ast.Call):
            prim = _blocking_prim(node)
            if prim is not None:
                flag_held = bool(held)
                if flag_held and prim.endswith(".wait"):
                    # Condition.wait RELEASES its own lock: `with c: c.wait()`
                    # (or `with lock: cond.wait()` where cond wraps lock) is
                    # the condvar idiom, not blocking-while-holding — unless
                    # OTHER locks are also held, which stay held while asleep
                    owner = node.func.value if isinstance(
                        node.func, ast.Attribute) else None
                    olid = self.model.lock_id_for(owner, self.info.cls) \
                        if owner is not None else None
                    released = {olid, self.model.cond_of.get(olid)} - {None}
                    if released and all(h in released for h in held):
                        flag_held = False
                self.direct_blocking.append((prim, node.lineno, flag_held))
            callee = self.model.resolve_call(node, self.info.cls)
            if callee is not None:
                self.calls.append((callee, node.lineno, held))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                self._record_mutation(t, node.lineno, bool(held))
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._record_mutation(t, node.lineno, bool(held))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _record_mutation(self, target: ast.AST, line: int,
                         held: bool) -> None:
        # self.attr = / self.attr[k] = / del self.attr
        if isinstance(target, ast.Subscript):
            target = target.value
        chain = _attr_chain(target)
        if chain and chain.startswith("self.") and self.info.cls:
            attr = chain[len("self."):]
            if "." not in attr:
                self.mutations.append((attr, line, held))


# ---------------------------------------------------------------------------
# conc-handrolled-pipeline: worker pools belong behind storage/pipeline.py
# ---------------------------------------------------------------------------

# the blessed executor seam itself (PipelineExecutor/SerialLane)
_PIPELINE_SEAM = os.path.join("storage", "pipeline.py")
_QUEUEISH_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                   "deque"}


class _PipelineScanner(ast.NodeVisitor):
    """Per enclosing class (None = module level): Thread constructions
    INSIDE a loop/comprehension (a worker-pool spawn) and queue-ish
    constructions. A scope showing both is a hand-rolled pipeline: it
    has its own (unmonitored, un-fault-injected, un-heartbeated)
    scheduling instead of the storage/pipeline.py executor seam. Single
    background drains (one Thread + one queue, the exporter/reporter
    idiom) do not flag — the loop-spawn is what makes it a pool."""

    def __init__(self):
        self._cls_stack: list[ast.ClassDef | None] = [None]
        self._loop_depth = 0
        self.pool_spawns: dict[ast.ClassDef | None, int] = {}
        self.queues: set[ast.ClassDef | None] = set()

    def visit_ClassDef(self, node: ast.ClassDef):
        self._cls_stack.append(node)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _loop
    visit_ListComp = visit_SetComp = visit_GeneratorExp = _loop

    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        leaf = chain.rsplit(".", 1)[-1] if chain else None
        scope = self._cls_stack[-1]
        if leaf == "Thread" and self._loop_depth > 0:
            self.pool_spawns.setdefault(scope, node.lineno)
        elif leaf in _QUEUEISH_CTORS:
            self.queues.add(scope)
        self.generic_visit(node)


def _check_handrolled_pipelines(mod: Module):
    if mod.rel == _PIPELINE_SEAM:
        return  # the one blessed executor seam
    sc = _PipelineScanner()
    sc.visit(mod.tree)
    for scope, line in sorted(sc.pool_spawns.items(),
                              key=lambda kv: kv[1]):
        if scope not in sc.queues:
            continue  # loop-spawned threads without a queue: a server
            # accept loop / per-task spawn, not a pipeline
        where = scope.name if scope is not None else "module scope"
        yield Finding(
            "conc-handrolled-pipeline", mod.path, line,
            f"{where} spawns worker threads in a loop AND owns a work "
            f"queue — a hand-rolled pipeline outside the executor seam. "
            f"Use storage/pipeline.py (PipelineExecutor / run_stages / "
            f"SerialLane): one pool, one saturation story "
            f"(inv-queue-gauge), one fault surface (pipeline.task), one "
            f"watchdog heartbeat (waive only for deliberate stand-alone "
            f"harnesses)")


def check(proj: Project):
    for mod in proj.modules:
        yield from _check_module(mod)
        yield from _check_handrolled_pipelines(mod)


def _check_module(mod: Module):
    model = _ModuleModel(mod)
    walkers: dict[str, _FuncWalker] = {}
    for q, info in model.funcs.items():
        w = _FuncWalker(model, info)
        w.walk()
        walkers[q] = w

    # ---- fixpoint: transitive acquires + blocking through calls ----------
    for q, info in model.funcs.items():
        info.acquires = set(walkers[q].direct_acquires)
        info.blocking = {p: p for p, _l, _h in walkers[q].direct_blocking}
    changed = True
    while changed:
        changed = False
        for q, info in model.funcs.items():
            for callee, _line, _held in walkers[q].calls:
                ci = model.funcs[callee]
                if not ci.acquires <= info.acquires:
                    info.acquires |= ci.acquires
                    changed = True
                for prim, via in ci.blocking.items():
                    if prim not in info.blocking:
                        info.blocking[prim] = f"{callee} -> {via}"
                        changed = True

    # ---- order graph: direct with-nesting edges + edges through calls ----
    # edge key (A, B) -> list of (line, via)
    edges: dict[tuple[str, str], list[tuple[int, str]]] = {}
    for q, w in walkers.items():
        for a, b, line, via in w.edges:
            edges.setdefault((a, b), []).append((line, via))
        for callee, line, held in w.calls:
            if not held:
                continue
            ci = model.funcs[callee]
            for b in ci.acquires:
                for a in held:
                    edges.setdefault((a, b), []).append((line, f"via {callee}()"))

    # reachability for inversion detection
    adj: dict[str, set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    reported: set[tuple[str, str, int]] = set()
    for (a, b), sites in sorted(edges.items()):
        if a == b:
            # re-acquiring a lock already held: deadlock unless reentrant
            if model.is_reentrant(a) is False:
                for line, via in sites:
                    key = (a, b, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    suffix = f" ({via})" if via else ""
                    yield Finding(
                        "lock-order", mod.path, line,
                        f"non-reentrant lock {a} re-acquired while already "
                        f"held{suffix} — self-deadlock")
            continue
        if reaches(b, a):
            for line, via in sites:
                key = (a, b, line)
                if key in reported:
                    continue
                reported.add(key)
                suffix = f" ({via})" if via else ""
                yield Finding(
                    "lock-order", mod.path, line,
                    f"acquires {b} while holding {a}{suffix}, but the "
                    f"reverse order {b} -> {a} also exists in this module "
                    f"— two threads entering from both ends deadlock")

    # ---- blocking calls under a held lock --------------------------------
    for q, w in walkers.items():
        for prim, line, held in w.direct_blocking:
            if held:
                yield Finding(
                    "lock-blocking-call", mod.path, line,
                    f"{q} calls blocking {prim}() while holding a lock — "
                    f"every other thread needing that lock stalls on the "
                    f"I/O; move it outside the critical section")
        for callee, line, held in w.calls:
            if not held:
                continue
            ci = model.funcs[callee]
            for prim, via in sorted(ci.blocking.items()):
                yield Finding(
                    "lock-blocking-call", mod.path, line,
                    f"{q} calls {callee}() under a lock, which reaches "
                    f"blocking {prim}() ({via})")

    # ---- guarded-attribute discipline ------------------------------------
    yield from _check_guarded_attrs(mod, model, walkers)


def _check_guarded_attrs(mod: Module, model: _ModuleModel,
                         walkers: dict[str, _FuncWalker]):
    # methods whose EVERY intra-class call site runs with a lock held are
    # themselves lock-held context (the `_foo_locked` helper convention);
    # computed to a fixpoint since such helpers call further helpers
    by_class: dict[str, list[str]] = {}
    for q, info in model.funcs.items():
        if info.cls is not None:
            by_class.setdefault(info.cls, []).append(q)

    lock_attrs = {lid.split(".", 1)[1] for lid in model.locks
                  if not lid.startswith(model.mod_name + ".")}

    for cls, methods in by_class.items():
        held_context: set[str] = set()
        changed = True
        while changed:
            changed = False
            for q in methods:
                if q in held_context:
                    continue
                callers = []
                for cq in methods:
                    for callee, _line, held in walkers[cq].calls:
                        if callee == q:
                            callers.append(bool(held) or cq in held_context)
                if callers and all(callers):
                    held_context.add(q)
                    changed = True

        # private helpers reachable ONLY from __init__ run before the
        # object is shared between threads — their writes are
        # pre-concurrency, like __init__'s own
        init_q = f"{cls}.__init__"
        init_only: set[str] = set()
        changed = True
        while changed:
            changed = False
            for q in methods:
                meth = q.split(".", 1)[1]
                if q in init_only or not meth.startswith("_") \
                        or meth == "__init__":
                    continue
                callers = [cq for cq in methods
                           for callee, _l, _h in walkers[cq].calls
                           if callee == q]
                if callers and all(
                        cq == init_q or cq in init_only for cq in callers):
                    init_only.add(q)
                    changed = True

        guarded: dict[str, list[tuple[str, int]]] = {}
        unguarded: dict[str, list[tuple[str, int]]] = {}
        for q in methods:
            info = model.funcs[q]
            meth_name = q.split(".", 1)[1]
            in_held_ctx = q in held_context
            for attr, line, held in walkers[q].mutations:
                if attr in lock_attrs or _LOCKISH_NAME.search(attr):
                    continue
                if held or in_held_ctx:
                    guarded.setdefault(attr, []).append((q, line))
                elif meth_name != "__init__" and q not in init_only:
                    unguarded.setdefault(attr, []).append((q, line))
        for attr, sites in sorted(unguarded.items()):
            g = guarded.get(attr)
            if not g:
                continue
            gq, gline = g[0]
            for q, line in sites:
                yield Finding(
                    "lock-guarded-mutation", mod.path, line,
                    f"{q} mutates self.{attr} with no lock held, but "
                    f"{gq} (line {gline}) mutates it under a lock — either "
                    f"the guard is required (race) or it isn't (waive)")
