#!/usr/bin/env python3
"""Static observability pass (wired into run_tests.sh).

Two invariants, both cheap enough to run before every test lane:

1. Tracepoint constants in m3_tpu/utils/trace.py are UNIQUE — two
   tracepoints sharing a name would silently merge in every trace tree
   and /debug/traces filter.

2. Every fault point declared via utils/faults (faults.check /
   faults.torn_write / faults.wrap_io with a literal point name) lives in
   a module that also instruments that seam — a metrics scope
   (instrument histogram/counter/timer) or a trace span. A fault point
   without observability is a seam we can break but not see.

Exit code 0 = clean; 1 = violations (each printed with file:line).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "m3_tpu")

# modules whose fault-point mentions are documentation or test scaffolding,
# not production seams
EXEMPT = {
    os.path.join("utils", "faults.py"),      # the registry itself (docs)
    os.path.join("tools", "race_check.py"),  # stress harness
}

# call attributes that count as "instrumented" when referenced in a module
_OBS_ATTRS = {"span", "histogram", "observe", "counter", "timer", "gauge",
              "subscope", "root_scope"}


def _tracepoint_constants(path: str) -> list[tuple[str, str]]:
    tree = ast.parse(open(path).read())
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            name = node.targets[0].id
            if name.startswith("_"):
                continue
            out.append((name, node.value.value))
    return out


class _Scanner(ast.NodeVisitor):
    def __init__(self):
        self.fault_points: list[tuple[str, int]] = []  # (point, lineno)
        self.instrumented = False

    def visit_Call(self, node: ast.Call):
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if attr in ("check", "torn_write", "wrap_io"):
            owner = getattr(fn, "value", None)
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if owner_name in ("faults", None) or attr == "check":
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str) and "." in arg.value:
                        self.fault_points.append((arg.value, node.lineno))
                        break
        if attr in _OBS_ATTRS:
            self.instrumented = True
        self.generic_visit(node)


def main() -> int:
    failures: list[str] = []

    # 1. tracepoint uniqueness
    tp_path = os.path.join(PKG, "utils", "trace.py")
    seen: dict[str, str] = {}
    for name, value in _tracepoint_constants(tp_path):
        if value in seen:
            failures.append(
                f"{tp_path}: tracepoint {name} duplicates {seen[value]} "
                f"(both {value!r})")
        seen[value] = name

    # 2. fault points have observability at their seam
    catalog: dict[str, list[str]] = {}
    for dirpath, _dirs, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, PKG)
            if rel in EXEMPT:
                continue
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError as e:
                failures.append(f"{path}: unparseable: {e}")
                continue
            sc = _Scanner()
            sc.visit(tree)
            if not sc.fault_points:
                continue
            for point, lineno in sc.fault_points:
                catalog.setdefault(point, []).append(f"{rel}:{lineno}")
            if not sc.instrumented:
                pts = ", ".join(p for p, _ in sc.fault_points)
                failures.append(
                    f"{path}: declares fault point(s) [{pts}] but has no "
                    f"metric scope or trace span at the seam")

    if failures:
        print("check_observability: FAILED", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"check_observability: OK — {len(seen)} tracepoints unique, "
          f"{len(catalog)} fault points instrumented at their seams")
    return 0


if __name__ == "__main__":
    sys.exit(main())
