#!/usr/bin/env python3
"""DEPRECATED shim — the observability invariants moved into m3lint.

The five checks this script used to run (tracepoint uniqueness, fault
seams instrumented, exemplar capture, exporter registration, admission
counters) are now m3lint's ``inv-*`` rule family
(tools/m3lint/rules_invariants.py), which run_tests.sh executes via
``python -m tools.m3lint`` before every lane, alongside the lock-
discipline and jax-purity families.

Kept as a working entry point so any script or muscle memory invoking
``python tools/check_observability.py`` still enforces the same
invariants (now the full m3lint set) with the same exit-code contract.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.m3lint.engine import main  # noqa: E402

if __name__ == "__main__":
    print("check_observability: absorbed into m3lint — running "
          "`python -m tools.m3lint`", file=sys.stderr)
    sys.exit(main())
