#!/usr/bin/env python3
"""Static observability pass (wired into run_tests.sh).

Four invariants, all cheap enough to run before every test lane:

1. Tracepoint constants in m3_tpu/utils/trace.py are UNIQUE — two
   tracepoints sharing a name would silently merge in every trace tree
   and /debug/traces filter.

2. Every fault point declared via utils/faults (faults.check /
   faults.torn_write / faults.wrap_io with a literal point name) lives in
   a module that also instruments that seam — a metrics scope
   (instrument histogram/counter/timer) or a trace span. A fault point
   without observability is a seam we can break but not see.

3. Every fault-catalog histogram seam is EXEMPLAR-CAPABLE: the three
   histogram entry points in utils/instrument (Scope.observe,
   Scope.histogram via observe, Scope.histogram_handle's closure) must
   each route through the exemplar-capture helper — the seams all
   observe through the Scope API, so capability is proven at the source.
   A seam histogram that can't pin a trace_id breaks the p99-bucket →
   stitched-trace link the OpenMetrics exposition promises.

4. Every service entrypoint (coordinator, dbnode, aggregator, kvd)
   registers the telemetry-exporter drainer (utils/export
   `exporter_from_config`) — a process outside the export plane is a
   blind spot the collector can't see.

5. Every per-tenant admission-control decision point
   (utils/tenantlimits: admit_write / admit_query) emits a counter
   (shed/allow per tenant), and the shed path carries the
   `tenant.admission.shed` tracepoint — a quota that can shed traffic
   invisibly is an outage an operator cannot attribute.

Exit code 0 = clean; 1 = violations (each printed with file:line).
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "m3_tpu")

# modules whose fault-point mentions are documentation or test scaffolding,
# not production seams
EXEMPT = {
    os.path.join("utils", "faults.py"),      # the registry itself (docs)
    os.path.join("tools", "race_check.py"),  # stress harness
}

# call attributes that count as "instrumented" when referenced in a module
_OBS_ATTRS = {"span", "histogram", "observe", "counter", "timer", "gauge",
              "subscope", "root_scope"}


def _tracepoint_constants(path: str) -> list[tuple[str, str]]:
    tree = ast.parse(open(path).read())
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            name = node.targets[0].id
            if name.startswith("_"):
                continue
            out.append((name, node.value.value))
    return out


class _Scanner(ast.NodeVisitor):
    def __init__(self):
        self.fault_points: list[tuple[str, int]] = []  # (point, lineno)
        self.instrumented = False

    def visit_Call(self, node: ast.Call):
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if attr in ("check", "torn_write", "wrap_io"):
            owner = getattr(fn, "value", None)
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if owner_name in ("faults", None) or attr == "check":
                for arg in node.args:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str) and "." in arg.value:
                        self.fault_points.append((arg.value, node.lineno))
                        break
        if attr in _OBS_ATTRS:
            self.instrumented = True
        self.generic_visit(node)


# service entrypoints that must register the exporter drainer: one per
# long-running process the platform ships
SERVICE_ENTRYPOINTS = (
    os.path.join("services", "coordinator.py"),
    os.path.join("services", "dbnode.py"),
    os.path.join("services", "aggregator.py"),
    os.path.join("cluster", "kvd.py"),
)


def _function_references(tree: ast.AST, func_name: str,
                         needle: str) -> bool:
    """Does the (possibly nested) function/closure named `func_name`
    reference `needle` anywhere in its body?"""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id == needle:
                    return True
                if isinstance(sub, ast.Attribute) and sub.attr == needle:
                    return True
    return False


def check_exemplar_capable(failures: list[str]) -> None:
    """Invariant 3: the Scope histogram entry points all capture
    exemplars, so every seam histogram (they all go through Scope) can
    pin a trace_id to its bucket."""
    path = os.path.join(PKG, "utils", "instrument.py")
    tree = ast.parse(open(path).read())
    # Scope.observe and the histogram_handle closure must consult the
    # exemplar trace source; _Histogram.observe_locked must accept and
    # store it. (Scope.histogram delegates to observe, so it inherits.)
    if not _function_references(tree, "observe", "_active_exemplar_trace") \
            and not _function_references(tree, "observe", "_exemplar"):
        failures.append(
            f"{path}: Scope.observe does not capture exemplars — seam "
            f"histograms lose the p99-bucket -> trace link")
    # the hot-path closure may inline the thread-local read instead of
    # calling the helper; either way it must write exemplar storage
    if not (_function_references(tree, "histogram_handle",
                                 "_active_exemplar_trace")
            or _function_references(tree, "histogram_handle", "exemplars")):
        failures.append(
            f"{path}: histogram_handle's hot-path closure does not capture "
            f"exemplars")
    if not _function_references(tree, "observe_locked", "exemplars"):
        failures.append(
            f"{path}: _Histogram.observe_locked has no exemplar storage")


def check_exporter_registered(failures: list[str]) -> None:
    """Invariant 4: every service entrypoint builds its exporter via
    utils/export.exporter_from_config."""
    for rel in SERVICE_ENTRYPOINTS:
        path = os.path.join(PKG, rel)
        try:
            tree = ast.parse(open(path).read())
        except (OSError, SyntaxError) as e:
            failures.append(f"{path}: unreadable/unparseable: {e}")
            continue
        found = any(
            isinstance(node, ast.Name) and node.id == "exporter_from_config"
            for node in ast.walk(tree)
        )
        if not found:
            failures.append(
                f"{path}: service entrypoint does not register the "
                f"telemetry exporter (exporter_from_config)")


def check_admission_observability(failures: list[str]) -> None:
    """Invariant 5: the tenant admission controller's decision points
    count every verdict, and sheds are trace-visible."""
    path = os.path.join(PKG, "utils", "tenantlimits.py")
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError) as e:
        failures.append(f"{path}: unreadable/unparseable: {e}")
        return
    # each decision point must route its verdict through the counting
    # helpers (which emit the per-tenant counters)
    for fn in ("admit_write", "admit_query"):
        counted = (_function_references(tree, fn, "_allow")
                   and _function_references(tree, fn, "_shed")) \
            or _function_references(tree, fn, "counter")
        if not counted:
            failures.append(
                f"{path}: decision point {fn} does not emit per-tenant "
                f"allow/shed counters")
    if not _function_references(tree, "_shed", "counter"):
        failures.append(
            f"{path}: the shed path does not emit a per-tenant counter")
    if not (_function_references(tree, "_shed", "span")
            and _function_references(tree, "_shed", "TENANT_SHED")):
        failures.append(
            f"{path}: the shed path does not carry the TENANT_SHED "
            f"tracepoint")


def main() -> int:
    failures: list[str] = []

    # 1. tracepoint uniqueness
    tp_path = os.path.join(PKG, "utils", "trace.py")
    seen: dict[str, str] = {}
    for name, value in _tracepoint_constants(tp_path):
        if value in seen:
            failures.append(
                f"{tp_path}: tracepoint {name} duplicates {seen[value]} "
                f"(both {value!r})")
        seen[value] = name

    # 2. fault points have observability at their seam
    catalog: dict[str, list[str]] = {}
    for dirpath, _dirs, files in os.walk(PKG):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, PKG)
            if rel in EXEMPT:
                continue
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError as e:
                failures.append(f"{path}: unparseable: {e}")
                continue
            sc = _Scanner()
            sc.visit(tree)
            if not sc.fault_points:
                continue
            for point, lineno in sc.fault_points:
                catalog.setdefault(point, []).append(f"{rel}:{lineno}")
            if not sc.instrumented:
                pts = ", ".join(p for p, _ in sc.fault_points)
                failures.append(
                    f"{path}: declares fault point(s) [{pts}] but has no "
                    f"metric scope or trace span at the seam")

    # 3 + 4: exemplar-capable seam histograms; exporter in every service
    check_exemplar_capable(failures)
    check_exporter_registered(failures)

    # 5: admission-control decisions are counted and sheds traced
    check_admission_observability(failures)

    if failures:
        print("check_observability: FAILED", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"check_observability: OK — {len(seen)} tracepoints unique, "
          f"{len(catalog)} fault points instrumented at their seams, "
          f"exemplar capture verified, exporter registered in "
          f"{len(SERVICE_ENTRYPOINTS)} service entrypoints, admission "
          f"decision points counted + shed path traced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
