"""TSan-lane parity driver: the test_native*/hostops assertions, re-run
against the ThreadSanitizer builds (native/tsan/*.so).

Why not just `pytest` under TSan?  ctypes can only load a
`-fsanitize=thread` library when libtsan is LD_PRELOADed into the whole
interpreter, and in this image pytest deadlocks under that preload (its
capture layer and TSan's runtime fight over stdio).  Plain Python
workloads run fine — m3_tpu/tools/race_check.py has relied on that since
PR 1 — so the tsan lane splits the work:

* ``pytest tests/test_race_native.py`` (uninstrumented pytest) spawns
  its OWN preloaded children: the planted-race sensitivity check plus
  race_check's threaded race workloads;
* this driver re-runs the core test_native.py / test_native_hostops.py
  parity battery in ONE preloaded child with M3TSZ_SO/M3HOSTOPS_SO
  swapped to the instrumented builds — proving the TSan artifacts are
  not just race-silent but bit-exact with the production builds.

Exit codes: 0 green, 66 TSan reported a race (TSAN_OPTIONS exitcode),
1 parity failure.

NOTE: the child must not touch ``np.testing`` — its assert machinery
deadlocks under the TSan runtime on this kernel the same way pytest's
capture layer does.  Comparisons use plain ``np.array_equal`` /
``np.allclose`` (verified TSan-safe).
"""

from __future__ import annotations

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHILD_ENV = "M3_TSAN_NATIVE_CHILD"


def _parent() -> int:
    sys.path.insert(0, _REPO)
    from m3_tpu.tools.race_check import _build_tsan, _libtsan_path

    outs = _build_tsan()  # cached: rebuilds only when the .cpp is newer
    env = dict(os.environ)
    env.update({
        _CHILD_ENV: "1",
        "LD_PRELOAD": _libtsan_path(),
        "M3TSZ_SO": outs["m3tsz.cpp"],
        "M3HOSTOPS_SO": outs["hostops.cpp"],
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "TSAN_OPTIONS": os.environ.get(
            "TSAN_OPTIONS", "exitcode=66 halt_on_error=0"),
    })
    r = subprocess.run([sys.executable, "-u", os.path.abspath(__file__)],
                       env=env, cwd=_REPO, timeout=900)
    if r.returncode == 0:
        print("tsan_native: parity battery green against the TSan builds")
    elif r.returncode == 66:
        print("tsan_native: ThreadSanitizer reported a data race — see "
              "report above", file=sys.stderr)
    else:
        print(f"tsan_native: FAILED (rc={r.returncode})", file=sys.stderr)
    return r.returncode


# ---------------------------------------------------------------------------
# child: the instrumented parity battery
# ---------------------------------------------------------------------------

_START = 1_599_998_400_000_000_000


def _eq(a, b, err_msg: str = "") -> None:
    import numpy as np

    assert np.array_equal(np.asarray(a), np.asarray(b)), \
        f"arrays differ {err_msg}"


def _close(a, b, rtol: float, atol: float, err_msg: str = "") -> None:
    import numpy as np

    assert np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True), \
        f"arrays not close {err_msg}"


def _series(rng, n=150, unit_step=10**9, scale=60):
    import numpy as np

    times = _START + np.cumsum(rng.integers(1, scale, n)) * unit_step
    return times.astype(np.int64), rng.normal(100, 25, n)


def _codec_battery() -> None:
    import numpy as np

    from m3_tpu.encoding.m3tsz import Encoder, native
    from m3_tpu.encoding.m3tsz import decode as py_decode
    from m3_tpu.utils.xtime import TimeUnit

    print("  codec: imports done", flush=True)
    assert native.available(), "tsan m3tsz build failed to load"
    print("  codec: tsan build loaded", flush=True)
    rng = np.random.default_rng(42)

    # bit-exact vs the Python scalar codec + roundtrip + cross decode
    times, values = _series(rng)
    stream = native.encode_series(times, values, _START, TimeUnit.SECOND)
    enc = Encoder(_START, int_optimized=False)
    for t, v in zip(times, values):
        enc.encode(int(t), float(v), TimeUnit.SECOND)
    assert stream == enc.stream(), "native stream != python stream"
    dt, dv = native.decode_series(stream, TimeUnit.SECOND)
    _eq(dt, times)
    _eq(dv, values)
    assert [d.value for d in py_decode(stream, int_optimized=False)] == \
        list(values)
    print("  codec: v1 bit-exact + roundtrip + cross decode", flush=True)

    # nanosecond unit
    tn, vn = _series(rng, unit_step=1, scale=10**10)
    sn = native.encode_series(tn, vn, _START, TimeUnit.NANOSECOND)
    dtn, dvn = native.decode_series(sn, TimeUnit.NANOSECOND)
    _eq(dtn, tn)
    _eq(dvn, vn)

    # special values
    ts = _START + (np.arange(8) + 1) * 10**9
    vs = np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e300, 1e-300, 7.0])
    _, got = native.decode_series(
        native.encode_series(ts, vs, _START, TimeUnit.SECOND),
        TimeUnit.SECOND)
    for a, b in zip(got, vs):
        assert a == b or (np.isnan(a) and np.isnan(b))
    print("  codec: ns unit + special values", flush=True)

    # v2 batch: bit-identical to v1, threaded roundtrip, ragged n_points
    B, T = 64, 100
    bt = np.stack([_series(rng, n=T)[0] for _ in range(B)])
    bv = np.stack([_series(rng, n=T)[1] for _ in range(B)])
    streams = native.encode_batch(bt, bv, np.full(B, _START),
                                  TimeUnit.SECOND, threads=4)
    for b in range(0, B, 7):
        assert streams[b] == native.encode_series(
            bt[b], bv[b], _START, TimeUnit.SECOND)
    dbt, dbv, ns = native.decode_batch(streams, TimeUnit.SECOND,
                                       max_points=T, threads=4)
    assert (ns == T).all()
    _eq(dbt[:, :T], bt)
    _eq(dbv[:, :T].view(np.float64), bv)

    n_points = np.array([T, 0, 10, T, 1, 25, T, 3], np.int32)
    streams = native.encode_batch(bt[:8], bv[:8], np.full(8, _START),
                                  TimeUnit.SECOND, n_points=n_points)
    _, _, ns = native.decode_batch(streams, TimeUnit.SECOND, max_points=T)
    _eq(ns, n_points)

    rate, lt, lv = native.bench_roundtrip_batch(
        bt, bv, _START, TimeUnit.SECOND, threads=2)
    assert rate > 0
    _eq(lt, bt[-1])
    print("  codec: v2 batch bit-identical + threaded roundtrip", flush=True)


def _hostops_battery() -> None:
    import numpy as np

    from m3_tpu.ops import native_hostops, windowed_agg
    from m3_tpu.query.windows import NS, RaggedSeries, extrapolated_rate

    assert native_hostops.available(), "tsan hostops build failed to load"

    def numpy_groups(e, w, v, t):
        os.environ["M3_TPU_NATIVE_OPS"] = "0"
        try:
            return windowed_agg.aggregate_groups(
                e, w, v, order_seq=np.arange(len(e)), times=t,
                need_sorted=True)
        finally:
            os.environ.pop("M3_TPU_NATIVE_OPS", None)

    rng = np.random.default_rng(0)
    n = 20_000
    e = rng.integers(0, 37, n).astype(np.int64)
    w = rng.integers(0, 5, n).astype(np.int64)
    v = rng.normal(100, 25, n)
    t = rng.integers(0, 50, n).astype(np.int64)
    t[rng.integers(0, n, n // 4)] = 7  # ties: append-order tiebreak
    ge_n, gw_n, st_n, vq_n, off_n = numpy_groups(e, w, v, t)
    ge, gw, st, vq, off = native_hostops.agg_groups(e, w, v, t)
    _eq(ge, ge_n)
    _eq(gw, gw_n)
    _eq(off, off_n)
    for k in ("count", "min", "max", "last"):
        _eq(st[k], st_n[k], err_msg=k)
    for k in ("sum", "sumsq", "mean", "stdev"):
        _close(st[k], st_n[k], 1e-9, 1e-9, k)
    _eq(vq, vq_n)
    print("  hostops: agg_groups parity (20k, ties)", flush=True)

    # adversarial int64 ranges: comparison-sort fallback, no UB
    imin, imax = np.iinfo(np.int64).min, np.iinfo(np.int64).max
    n = 4_096
    e = rng.integers(-2**62, 2**62, n).astype(np.int64)
    w = rng.integers(-2**62, 2**62, n).astype(np.int64)
    e[:4] = [imin, imax, imin + 1, imax - 1]
    w[:4] = [imax, imin, imax - 1, imin + 1]
    e[4:8] = e[:4]
    w[4:8] = w[:4]
    v = rng.normal(0, 1, n)
    t = rng.integers(0, 100, n).astype(np.int64)
    ge_n, gw_n, st_n, _, off_n = numpy_groups(e, w, v, t)
    ge, gw, st, _, off = native_hostops.agg_groups(e, w, v, t)
    _eq(ge, ge_n)
    _eq(gw, gw_n)
    _eq(off, off_n)
    _eq(st["last"], st_n["last"])
    print("  hostops: int64-spanning ids (stable_sort path)", flush=True)

    # rate_csr parity vs the numpy Prometheus rate math
    per = []
    for _ in range(40):
        T = int(rng.integers(0, 50))
        ts = np.unique(np.sort(rng.integers(0, 3600, T)).astype(np.int64) * NS)
        vv = rng.integers(0, 10, len(ts)).astype(np.float64).cumsum()
        per.append((ts, vv))
    raws = RaggedSeries.from_lists(per)
    eval_ts = np.arange(300, 3600, 60, dtype=np.int64) * NS
    for is_counter, is_rate in ((True, True), (True, False), (False, False)):
        got = native_hostops.rate_csr(raws.times, raws.values, raws.offsets,
                                      eval_ts, 300 * NS, is_counter, is_rate,
                                      threads=2)
        os.environ["M3_TPU_NATIVE_OPS"] = "0"
        try:
            want = extrapolated_rate(raws, eval_ts, 300 * NS, is_counter,
                                     is_rate)
        finally:
            os.environ.pop("M3_TPU_NATIVE_OPS", None)
        _close(got, want, 1e-9, 1e-12)
    print("  hostops: rate_csr parity x3 modes (threaded)", flush=True)


def _child() -> int:
    if _REPO not in sys.path:  # script-mode child: repo root for m3_tpu
        sys.path.insert(0, _REPO)
    print("tsan_native child: parity battery against "
          f"{os.environ.get('M3TSZ_SO')}", flush=True)
    _codec_battery()
    _hostops_battery()
    return 0


def main() -> int:
    if os.environ.get(_CHILD_ENV) != "1":
        return _parent()
    return _child()


if __name__ == "__main__":
    raise SystemExit(main())
