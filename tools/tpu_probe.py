"""Staged TPU probe: one timestamped line per stage so a hang is localized.

Run under a shell timeout; every line flushes immediately. Stages go from
trivial (constant add) to the real codec kernels at tiny shapes.
"""
import os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.time()


def log(msg):
    print(f"[{time.time() - T0:7.1f}s] {msg}", flush=True)


log("start; importing jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

log(f"jax {jax.__version__} imported")

cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.makedirs(cache_dir, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
log(f"compilation cache at {cache_dir}")

devs = jax.devices()
log(f"devices: {devs} platform={devs[0].platform}")

# stage 1: trivial eager op
x = jnp.float32(1.5) + jnp.float32(2.5)
log("eager add traced")
x.block_until_ready()
log(f"eager add done: {x}")

# stage 2: tiny jit
f = jax.jit(lambda a, b: a * b + 1.0)
y = f(jnp.ones((8, 8), jnp.float32), jnp.full((8, 8), 2.0, jnp.float32))
log("tiny jit dispatched")
y.block_until_ready()
log(f"tiny jit done sum={float(y.sum())}")

# stage 3: matmul on MXU
g = jax.jit(lambda a: a @ a)
z = g(jnp.ones((256, 256), jnp.bfloat16))
log("matmul dispatched")
z.block_until_ready()
log(f"matmul done [0,0]={float(z[0, 0])}")

# stage 4: int64/uint64 ops (codec uses u64 words — X64 rewriter territory)
h = jax.jit(lambda a: (a << 3) ^ (a >> 2))
w = h(jnp.arange(64, dtype=jnp.uint32))
w.block_until_ready()
log("uint32 shifts done")
try:
    h64 = jax.jit(lambda a: (a << 3) ^ (a >> 2))
    w64 = h64(jnp.arange(64, dtype=jnp.uint64))
    w64.block_until_ready()
    log("uint64 shifts done")
except Exception as e:  # noqa: BLE001
    log(f"uint64 shifts FAILED: {type(e).__name__}: {e}")

# stage 5: lax.scan (decoder shape)
def scan_body(c, t):
    return c + t, c * t

s = jax.jit(lambda xs: jax.lax.scan(scan_body, jnp.float32(0), xs))
cs, ys = s(jnp.ones((128,), jnp.float32))
jax.block_until_ready((cs, ys))
log("lax.scan done")

# stage 6: the real codec at tiny shape
log("importing m3tsz tpu codec")
from m3_tpu.encoding.m3tsz import tpu  # noqa: E402
from m3_tpu.utils.xtime import TimeUnit  # noqa: E402
from __graft_entry__ import _example_batch  # noqa: E402

for B, T in ((8, 8), (64, 16), (1024, 120)):
    times, vbits, start, n_points = _example_batch(B=B, T=T)
    jt, jv, js, jn = map(jnp.asarray, (times, vbits, start, n_points))
    cap = (64 + 80 * T + 11 + 63) // 64
    log(f"B={B} T={T}: tracing encode")
    blocks = tpu.encode_bits(jt, jv, js, jn, TimeUnit.SECOND, cap)
    log(f"B={B} T={T}: encode dispatched; blocking")
    jax.block_until_ready(blocks.words)
    log(f"B={B} T={T}: encode DONE overflow={bool(blocks.overflow)}")
    dec = tpu.decode(blocks.words, TimeUnit.SECOND, max_points=T)
    log(f"B={B} T={T}: decode dispatched; blocking")
    jax.block_until_ready(dec.times)
    import numpy as np
    ok = (np.asarray(dec.value_bits)[:, :T] == vbits).all() and (
        np.asarray(dec.times)[:, :T] == times
    ).all()
    log(f"B={B} T={T}: decode DONE correct={bool(ok)}")

log("ALL STAGES PASSED")
