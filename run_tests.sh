#!/bin/bash
# CPU-only test runner: clears PALLAS_AXON_POOL_IPS so the axon
# sitecustomize doesn't dial the TPU relay at interpreter startup (hangs
# every python process when the tunnel is down), and forces the CPU
# platform with an 8-device virtual mesh for sharding tests.
#
# Lanes:
#   run_tests.sh fast   — deselects the `slow`-marked files (multi-process
#                         clusters, XLA parity sweeps); target < 2 min
#   run_tests.sh [...]  — full suite (extra args pass through to pytest)
ARGS=("$@")
if [ "${1:-}" = "fast" ]; then
  shift
  ARGS=(-m "not slow" "$@")
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest tests/ -q "${ARGS[@]}"
