#!/bin/bash
# CPU-only test runner: clears PALLAS_AXON_POOL_IPS so the axon
# sitecustomize doesn't dial the TPU relay at interpreter startup (hangs
# every python process when the tunnel is down), and forces the CPU
# platform with an 8-device virtual mesh for sharding tests.
#
# Lanes:
#   run_tests.sh fast   — deselects the `slow`-marked files (multi-process
#                         clusters, XLA parity sweeps); target < 2 min
#   run_tests.sh chaos  — opt-in seeded fault-injection stage: the
#                         crash-recovery loop runs M3_TPU_CHAOS_ITERS
#                         (default 200) kill-mid-flush iterations per
#                         schedule, and the consensus sweep runs the same
#                         number of partition/leader-kill/heal rounds
#                         against the raft-lite metadata plane under a
#                         virtual clock; never part of tier-1. (PR 20)
#                         The lane arms M3_TPU_WIRE=packed so every
#                         inter-node RPC the schedules drive rides the
#                         binary frames; export M3_TPU_WIRE=json to rerun
#                         the identical schedules over the legacy JSON
#                         hatch (byte-identical results — the fallback
#                         contract tests/test_wire.py pins)
#   run_tests.sh rig    — opt-in PROCESS-LEVEL production rig: real
#                         spawned dbnodes + 3-replica quorum kvd +
#                         coordinator + aggregator under seeded
#                         kill/partition chaos and live load
#                         (M3_TPU_RIG_SECONDS schedule budget, ~60s wall
#                         with spawn/verify overhead). Asserts zero
#                         acked-write loss, the pair-median p99 SLO, AND
#                         (PR 9) the anti-entropy convergence audit:
#                         every replica pair reaches per-(shard, block)
#                         rollup-digest equality within the repair-cycle
#                         budget, driven by the nodes' own RepairDaemons.
#                         (PR 17) The lane also runs the topology
#                         ELASTICITY episode: add-node -> paced verified
#                         drain -> rolling restart under live load with
#                         chaos overlapping the placement changes, zero
#                         acked-write loss through every handoff, and the
#                         post-episode convergence audit. Both episodes
#                         share the M3_TPU_RIG_SECONDS budget; never
#                         tier-1. (PR 20) Like the chaos lane, the rig
#                         runs with M3_TPU_WIRE=packed armed, so repair
#                         streams, rollup digests, and coordinator reads
#                         all ride the binary frames under kill/partition
#                         chaos
#   run_tests.sh tsan   — opt-in ThreadSanitizer stage for the native
#                         layer: (1) pytest tests/test_race_native.py
#                         (uninstrumented pytest; its tests spawn their
#                         own libtsan-preloaded children — planted-race
#                         sensitivity + race_check's threaded workloads),
#                         then (2) tools/tsan_native.py re-runs the
#                         test_native*/test_native_hostops parity battery
#                         in a preloaded child with M3TSZ_SO/M3HOSTOPS_SO
#                         swapped to the native/tsan builds. pytest itself
#                         cannot run under the preload in this image (its
#                         capture layer deadlocks against the TSan
#                         runtime), which is why the lane splits this way;
#                         never tier-1
#   run_tests.sh [...]  — full suite (extra args pass through to pytest)
#
# Static analysis gate (every lane): tools/m3lint — lock discipline
# (order inversions, blocking calls under locks, unguarded mutation of
# guarded attrs), jax jit-purity/recompile hazards, and the project
# invariants (tracepoints, fault seams, exemplars, exporter, admission,
# histogram catalog, crash-swallowing excepts). Zero unwaived findings
# or the lane does not run. Budget ~10s; see README "Static analysis &
# concurrency checking".
cd "$(dirname "$0")" || exit 1
# same env guard as the lanes below: a set-but-dead PALLAS_AXON_POOL_IPS
# hangs ANY python at interpreter startup, lint gate included
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m tools.m3lint || exit 1
ARGS=("$@")
if [ "${1:-}" = "fast" ]; then
  shift
  ARGS=(-m "not slow" "$@")
elif [ "${1:-}" = "chaos" ]; then
  shift
  exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    M3_TPU_CHAOS_ITERS="${M3_TPU_CHAOS_ITERS:-200}" \
    M3_TPU_WIRE="${M3_TPU_WIRE:-packed}" \
    python -m pytest tests/test_crash_recovery.py tests/test_fault_injection.py \
    tests/test_consensus.py \
    -q -m chaos "$@"
elif [ "${1:-}" = "rig" ]; then
  shift
  exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    M3_TPU_RIG_SECONDS="${M3_TPU_RIG_SECONDS:-20}" \
    M3_TPU_WIRE="${M3_TPU_WIRE:-packed}" \
    python -m pytest tests/test_rig.py -q -m chaos "$@"
elif [ "${1:-}" = "tsan" ]; then
  shift
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_race_native.py -q "$@" || exit 1
  exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python tools/tsan_native.py
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest tests/ -q "${ARGS[@]}"
