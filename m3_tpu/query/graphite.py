"""Graphite engine: carbon ingest, path model, expression language, and
the render function library.

Role parity with the reference's Graphite support
(/root/reference/src/query/graphite — lexer/parser, native/compiler.go,
110 builtin functions in native/builtin_functions.go, and the storage
adapter mapping dotted paths to tag queries) and the carbon line-protocol
parser (src/metrics/carbon/parser.go). Dotted paths map to positional tags
(__g0__, __g1__, ...) exactly like the reference's graphite storage
adapter, so Graphite data lives in the same TSDB namespaces as Prometheus
data.

The function library covers all 110 reference builtins (plus graphite-web
aliases like round/time/randomWalk); registering more is adding an entry
to FUNCTIONS. timeShift is a special form in GraphiteEngine._eval because
it re-evaluates its subtree over a shifted window.
"""

from __future__ import annotations

import math
import re
import zlib
from dataclasses import dataclass

import numpy as np

from m3_tpu.index.query import ConjunctionQuery, RegexpQuery, TermQuery

NS = 10**9


def path_to_tags(path: bytes) -> list[tuple[bytes, bytes]]:
    """'web.host1.cpu' -> [(__g0__, web), (__g1__, host1), (__g2__, cpu)]."""
    return [
        (f"__g{i}__".encode(), part)
        for i, part in enumerate(path.split(b"."))
    ]


def tags_to_path(tags: dict[bytes, bytes]) -> bytes:
    parts = []
    i = 0
    while True:
        v = tags.get(f"__g{i}__".encode())
        if v is None:
            break
        parts.append(v)
        i += 1
    return b".".join(parts)


def _glob_part_to_regex(part: str) -> str:
    out = []
    for seg in re.split(r"(\*|\?|\{[^}]*\}|\[[^\]]*\])", part):
        if seg == "*":
            out.append("[^.]*")
        elif seg == "?":
            out.append("[^.]")
        elif seg.startswith("{") and seg.endswith("}"):
            out.append("(?:" + "|".join(re.escape(a) for a in seg[1:-1].split(",")) + ")")
        elif seg.startswith("[") and seg.endswith("]"):
            out.append(seg)
        else:
            out.append(re.escape(seg))
    return "".join(out)


def path_query(pattern: str):
    """Graphite glob path -> index query over positional tags."""
    parts = pattern.split(".")
    qs = []
    for i, part in enumerate(parts):
        name = f"__g{i}__".encode()
        if part == "*":
            from m3_tpu.index.query import FieldQuery

            qs.append(FieldQuery(name))
        elif any(c in part for c in "*?{}[]"):
            qs.append(RegexpQuery(name, _glob_part_to_regex(part)))
        else:
            qs.append(TermQuery(name, part.encode()))
    # exact depth: the next position must not exist
    from m3_tpu.index.query import FieldQuery, NegationQuery

    qs.append(NegationQuery(FieldQuery(f"__g{len(parts)}__".encode())))
    return ConjunctionQuery(tuple(qs))


def path_prefix_query(pattern: str):
    """Like path_query but WITHOUT the exact-depth constraint: matches any
    series whose path starts with the pattern (used by /metrics/find)."""
    q = path_query(pattern)
    return ConjunctionQuery(tuple(q.queries[:-1]))


# ---------------------------------------------------------------------------
# carbon line protocol
# ---------------------------------------------------------------------------


def parse_carbon_line(line: bytes):
    """'path value timestamp' -> (path, value, t_ns) or None for junk."""
    parts = line.strip().split()
    if len(parts) != 3:
        return None
    try:
        value = float(parts[1])
        ts = float(parts[2])
    except ValueError:
        return None
    return parts[0], value, int(ts * NS)


class CarbonIngester:
    """TCP line-protocol server writing into the database (the reference's
    coordinator carbon ingest, ingest/carbon/ingest.go)."""

    def __init__(self, db, namespace: str = "default", host: str = "127.0.0.1",
                 port: int = 0, writer=None):
        import socket
        import threading

        self.db = db
        self.namespace = namespace
        self.writer = writer  # optional DownsamplerAndWriter (rules path)
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self._closed = False
        self.num_ingested = 0
        self.num_errors = 0
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        import threading

        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        buf = b""
        try:
            while not self._closed:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    parsed = parse_carbon_line(line)
                    if parsed is None:
                        continue
                    path, value, t_ns = parsed
                    try:
                        if self.writer is not None:
                            from m3_tpu.metrics.aggregation import MetricType

                            self.writer.write(MetricType.GAUGE, b"",
                                              path_to_tags(path), t_ns, value)
                        else:
                            self.db.write_tagged(
                                self.namespace, b"", path_to_tags(path),
                                t_ns, value,
                            )
                        self.num_ingested += 1
                    except Exception:
                        # a bad datapoint must not kill the connection
                        self.num_errors += 1
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# render expression language
# ---------------------------------------------------------------------------


@dataclass
class Series:
    name: bytes
    times: np.ndarray  # [T] step grid (ns)
    values: np.ndarray  # [T] float64 (NaN = missing)


class GraphiteError(ValueError):
    pass


# a path segment char may not be a bare comma (argument separator); commas
# are only meaningful inside {a,b} alternations
_TOKEN = re.compile(
    r"\s*(?:(?P<num>-?\d+\.?\d*)(?![A-Za-z0-9_.\-*?{\[])"
    r"|(?P<str>'[^']*'|\"[^\"]*\")"
    r"|(?P<ident>[A-Za-z_][A-Za-z0-9_]*)(?=\()"
    r"|(?P<path>(?:[A-Za-z0-9_.\-*?\[\]:=]|\{[^}]*\})+)"
    r"|(?P<lp>\()|(?P<rp>\))|(?P<comma>,))"
)


def parse_target(expr: str, pos: int = 0):
    """Parse one render target expression -> AST of ('call', name, args) /
    ('path', pattern) / ('num', x) / ('str', s)."""
    m = _TOKEN.match(expr, pos)
    if not m:
        raise GraphiteError(f"parse error at {pos} in {expr!r}")
    if m.group("ident"):
        name = m.group("ident")
        pos = m.end()
        m2 = _TOKEN.match(expr, pos)
        if not m2 or not m2.group("lp"):
            raise GraphiteError(f"expected ( after {name}")
        pos = m2.end()
        args = []
        while True:
            m3 = _TOKEN.match(expr, pos)
            if m3 and m3.group("rp"):
                pos = m3.end()
                break
            arg, pos = parse_target(expr, pos)
            args.append(arg)
            m4 = _TOKEN.match(expr, pos)
            if m4 and m4.group("comma"):
                pos = m4.end()
            elif m4 and m4.group("rp"):
                pos = m4.end()
                break
            else:
                raise GraphiteError(f"expected , or ) at {pos} in {expr!r}")
        return ("call", name, args), pos
    if m.group("num"):
        return ("num", float(m.group("num"))), m.end()
    if m.group("str"):
        return ("str", m.group("str")[1:-1]), m.end()
    if m.group("path"):
        word = m.group("path")
        # graphite-web parses bare true/false as boolean literals, not paths
        if word in ("true", "True", "false", "False"):
            return ("bool", word.lower() == "true"), m.end()
        return ("path", word), m.end()
    raise GraphiteError(f"unexpected token at {pos} in {expr!r}")


class GraphiteEngine:
    """Evaluates render targets against the database."""

    def __init__(self, db, namespace: str = "default", resolve_tiers=True,
                 now_fn=None):
        import time as _time

        self.db = db
        self.namespace = namespace
        self.resolve_tiers = resolve_tiers
        self.now_fn = now_fn or _time.time_ns

    # -- fetch --

    def fetch(self, pattern: str, start_ns: int, end_ns: int, step_ns: int
              ) -> list[Series]:
        from m3_tpu.query import resolver

        ns_list = (resolver.resolve_namespaces(self.db, self.namespace,
                                               start_ns, end_ns,
                                               self.now_fn())
                   if self.resolve_tiers else [self.namespace])
        docs, series = resolver.fetch_tagged(
            self.db, ns_list, path_query(pattern), start_ns, end_ns,
            keep_empty=True)
        grid = np.arange(start_ns, end_ns, step_ns, dtype=np.int64)
        out = []
        order = sorted(range(len(docs)), key=lambda i: docs[i].series_id)
        for i in order:
            doc, (times, vbits) = docs[i], series[i]
            vals = np.full(len(grid), np.nan)
            if len(times):
                idx = np.searchsorted(grid, times, side="right") - 1
                ok = idx >= 0
                vals[idx[ok]] = vbits.view(np.float64)[ok]
            out.append(Series(tags_to_path(dict(doc.fields)), grid, vals))
        return out

    # -- evaluate --

    def render(self, target: str, start_ns: int, end_ns: int,
               step_ns: int = 60 * NS) -> list[Series]:
        ast, pos = parse_target(target)
        if pos != len(target.rstrip()):
            raise GraphiteError(f"trailing input in {target!r}")
        out = self._eval(ast, start_ns, end_ns, step_ns)
        if not isinstance(out, list):
            raise GraphiteError("target did not evaluate to series")
        return out

    def _eval(self, ast, start_ns, end_ns, step_ns):
        kind = ast[0]
        if kind == "path":
            return self.fetch(ast[1], start_ns, end_ns, step_ns)
        if kind == "num":
            return ast[1]
        if kind == "str":
            return ast[1]
        if kind == "bool":
            return ast[1]
        if kind == "call":
            _, name, args = ast
            if name == "timeShift":
                return self._time_shift(args, start_ns, end_ns, step_ns)
            fn = FUNCTIONS.get(name)
            if fn is None:
                raise GraphiteError(f"unknown function {name}()")
            vals = [self._eval(a, start_ns, end_ns, step_ns) for a in args]
            return fn(self, vals, start_ns, end_ns, step_ns)
        raise GraphiteError(f"bad ast {ast!r}")

    def _time_shift(self, args, start_ns, end_ns, step_ns):
        """Special form: re-evaluates the inner expression at a shifted
        window (works for aggregates/aliases, not just bare paths).
        Graphite sign semantics: unsigned and '-' shift back in time,
        '+' shifts forward."""
        from m3_tpu.metrics.policy import parse_go_duration

        if len(args) != 2 or args[1][0] != "str":
            raise GraphiteError("timeShift(expr, 'interval')")
        spec = args[1][1]
        mag = parse_go_duration(spec.lstrip("+-"))
        shift = mag if spec.startswith("+") else -mag
        inner = self._eval(args[0], start_ns + shift, end_ns + shift, step_ns)
        if not isinstance(inner, list):
            raise GraphiteError("timeShift expects series")
        return [Series(s.name, s.times - shift, s.values) for s in inner]


# -- function library ------------------------------------------------------

FUNCTIONS = {}


def register(name):
    def deco(fn):
        FUNCTIONS[name] = fn
        return fn

    return deco


def _combine(series: list[Series], op, name: bytes) -> list[Series]:
    if not series:
        return []
    stack = np.stack([s.values for s in series])
    with np.errstate(invalid="ignore"):
        vals = op(stack)
    return [Series(name, series[0].times, vals)]


def _flatten(args) -> list[Series]:
    out = []
    for a in args:
        if isinstance(a, list):
            out.extend(a)
    return out


@register("sumSeries")
def _sum_series(eng, args, *_):
    s = _flatten(args)

    def op(x):
        out = np.nansum(x, axis=0)
        # a column with no values is null, not 0 (nansum quirk)
        return np.where(np.isnan(x).all(axis=0), np.nan, out)

    return _combine(s, op, b"sumSeries")


@register("averageSeries")
@register("avg")
def _avg_series(eng, args, *_):
    s = _flatten(args)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return _combine(s, lambda x: np.nanmean(x, axis=0), b"averageSeries")


@register("maxSeries")
def _max_series(eng, args, *_):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return _combine(_flatten(args), lambda x: np.nanmax(x, axis=0), b"maxSeries")


@register("minSeries")
def _min_series(eng, args, *_):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return _combine(_flatten(args), lambda x: np.nanmin(x, axis=0), b"minSeries")


@register("countSeries")
def _count_series(eng, args, *_):
    s = _flatten(args)
    return _combine(s, lambda x: (~np.isnan(x)).sum(axis=0).astype(float),
                    b"countSeries")


@register("scale")
def _scale(eng, args, *_):
    series, factor = args[0], args[1]
    return [Series(s.name, s.times, s.values * factor) for s in series]


@register("offset")
def _offset(eng, args, *_):
    series, amount = args[0], args[1]
    return [Series(s.name, s.times, s.values + amount) for s in series]


@register("absolute")
def _absolute(eng, args, *_):
    return [Series(s.name, s.times, np.abs(s.values)) for s in args[0]]


@register("invert")
def _invert(eng, args, *_):
    with np.errstate(divide="ignore"):
        return [Series(s.name, s.times, 1.0 / s.values) for s in args[0]]


@register("derivative")
def _derivative(eng, args, *_):
    out = []
    for s in args[0]:
        d = np.concatenate([[np.nan], np.diff(s.values)])
        out.append(Series(s.name, s.times, d))
    return out


@register("nonNegativeDerivative")
def _nn_derivative(eng, args, *_):
    out = []
    for s in args[0]:
        d = np.concatenate([[np.nan], np.diff(s.values)])
        d = np.where(d < 0, np.nan, d)
        out.append(Series(s.name, s.times, d))
    return out


@register("perSecond")
def _per_second(eng, args, start, end, step):
    out = []
    for s in args[0]:
        d = np.concatenate([[np.nan], np.diff(s.values)])
        d = np.where(d < 0, np.nan, d) / (step / NS)
        out.append(Series(s.name, s.times, d))
    return out


@register("integral")
def _integral(eng, args, *_):
    out = []
    for s in args[0]:
        v = np.nancumsum(s.values)
        v[np.isnan(s.values)] = np.nan
        out.append(Series(s.name, s.times, v))
    return out


@register("movingAverage")
def _moving_average(eng, args, start, end, step):
    series, window = args[0], _window_points(args[1], step)
    out = []
    for s in series:
        v = s.values
        acc = np.full(len(v), np.nan)
        csum = np.nancumsum(np.concatenate([[0.0], v]))
        ccnt = np.cumsum(np.concatenate([[0], (~np.isnan(v)).astype(int)]))
        for i in range(len(v)):
            lo = max(0, i - window + 1)
            cnt = ccnt[i + 1] - ccnt[lo]
            if cnt:
                acc[i] = (csum[i + 1] - csum[lo]) / cnt
        out.append(Series(s.name, s.times, acc))
    return out


@register("keepLastValue")
def _keep_last(eng, args, *_):
    out = []
    for s in args[0]:
        v = s.values.copy()
        idx = np.where(np.isnan(v), 0, np.arange(len(v)))
        np.maximum.accumulate(idx, out=idx)
        filled = v[idx]
        filled[np.isnan(v) & (idx == 0) & np.isnan(v[0])] = np.nan
        out.append(Series(s.name, s.times, filled))
    return out


@register("transformNull")
def _transform_null(eng, args, *_):
    series = args[0]
    default = args[1] if len(args) > 1 else 0.0
    return [
        Series(s.name, s.times, np.where(np.isnan(s.values), default, s.values))
        for s in series
    ]


@register("alias")
def _alias(eng, args, *_):
    return [Series(args[1].encode(), s.times, s.values) for s in args[0]]


@register("aliasByNode")
def _alias_by_node(eng, args, *_):
    series = args[0]
    nodes = [int(a) for a in args[1:]]
    out = []
    for s in series:
        parts = s.name.split(b".")
        name = b".".join(parts[n] for n in nodes if -len(parts) <= n < len(parts))
        out.append(Series(name, s.times, s.values))
    return out


@register("groupByNode")
def _group_by_node(eng, args, start, end, step):
    series, node = args[0], int(args[1])
    agg = args[2] if len(args) > 2 else "sum"
    groups: dict[bytes, list[Series]] = {}
    for s in series:
        parts = s.name.split(b".")
        key = parts[node] if -len(parts) <= node < len(parts) else b""
        groups.setdefault(key, []).append(s)
    op = {
        "sum": lambda x: np.nansum(x, axis=0),
        "avg": lambda x: np.nanmean(x, axis=0),
        "max": lambda x: np.nanmax(x, axis=0),
        "min": lambda x: np.nanmin(x, axis=0),
    }[agg]
    out = []
    import warnings

    for key in sorted(groups):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out.extend(_combine(groups[key], op, key))
    return out


@register("highestMax")
def _highest_max(eng, args, *_):
    series, n = args[0], int(args[1])
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ranked = sorted(series, key=lambda s: -np.nanmax(s.values))
    return ranked[:n]


@register("highestCurrent")
def _highest_current(eng, args, *_):
    series, n = args[0], int(args[1])

    def cur(s):
        ok = s.values[~np.isnan(s.values)]
        return ok[-1] if len(ok) else -math.inf

    return sorted(series, key=lambda s: -cur(s))[:n]


@register("lowestCurrent")
def _lowest_current(eng, args, *_):
    series, n = args[0], int(args[1])

    def cur(s):
        ok = s.values[~np.isnan(s.values)]
        return ok[-1] if len(ok) else math.inf

    return sorted(series, key=cur)[:n]


@register("limit")
def _limit(eng, args, *_):
    return args[0][: int(args[1])]


@register("exclude")
def _exclude(eng, args, *_):
    rx = re.compile(args[1].encode())
    return [s for s in args[0] if not rx.search(s.name)]


@register("grep")
def _grep(eng, args, *_):
    rx = re.compile(args[1].encode())
    return [s for s in args[0] if rx.search(s.name)]


@register("averageAbove")
def _average_above(eng, args, *_):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return [s for s in args[0] if np.nanmean(s.values) > args[1]]


@register("currentAbove")
def _current_above(eng, args, *_):
    def cur(s):
        ok = s.values[~np.isnan(s.values)]
        return ok[-1] if len(ok) else -math.inf

    return [s for s in args[0] if cur(s) > args[1]]


@register("divideSeries")
def _divide_series(eng, args, *_):
    num, den = args[0], args[1]
    if len(den) != 1:
        raise GraphiteError("divideSeries requires a single divisor series")
    with np.errstate(divide="ignore", invalid="ignore"):
        return [
            Series(s.name, s.times, s.values / den[0].values) for s in num
        ]


@register("diffSeries")
def _diff_series(eng, args, *_):
    s = _flatten(args)
    if not s:
        return []
    first = s[0].values
    rest = np.stack([x.values for x in s[1:]]) if len(s) > 1 else np.zeros((1, len(first)))
    vals = np.where(np.isnan(first), np.nan,
                    first - np.nansum(rest, axis=0))
    return [Series(b"diffSeries", s[0].times, vals)]


@register("asPercent")
def _as_percent(eng, args, *_):
    series = args[0]
    if len(args) > 1 and isinstance(args[1], list):
        total = args[1][0].values
    elif len(args) > 1:
        total = args[1]
    else:
        total = np.nansum(np.stack([s.values for s in series]), axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        return [
            Series(s.name, s.times, 100.0 * s.values / total) for s in series
        ]


@register("summarize")
def _summarize(eng, args, start, end, step):
    from m3_tpu.metrics.policy import parse_go_duration

    series, interval = args[0], parse_go_duration(args[1])
    agg = args[2] if len(args) > 2 else "sum"
    op = {"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax, "min": np.nanmin}[agg]
    out = []
    import warnings

    for s in series:
        bucket = ((s.times - s.times[0]) // interval).astype(np.int64)
        n_buckets = int(bucket[-1]) + 1 if len(bucket) else 0
        times = s.times[0] + np.arange(n_buckets) * interval
        vals = np.full(n_buckets, np.nan)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for b in range(n_buckets):
                sel = s.values[bucket == b]
                if (~np.isnan(sel)).any():
                    vals[b] = op(sel)
        out.append(Series(s.name, times, vals))
    return out


@register("constantLine")
def _constant_line(eng, args, start, end, step):
    grid = np.arange(start, end, step, dtype=np.int64)
    return [Series(str(args[0]).encode(), grid, np.full(len(grid), args[0]))]


@register("sortByMaxima")
def _sort_by_maxima(eng, args, *_):
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return sorted(args[0], key=lambda s: -np.nanmax(s.values))


@register("sortByName")
def _sort_by_name(eng, args, *_):
    return sorted(args[0], key=lambda s: s.name)


# ---------------------------------------------------------------------------
# long-tail builtins (the most-used remainder of the reference's 110,
# query/graphite/native/builtin_functions.go)
# ---------------------------------------------------------------------------

import contextlib as _contextlib
import warnings as _warnings


@_contextlib.contextmanager
def _quiet():
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        yield


def _graphite_percentile(values: np.ndarray, n: float) -> float:
    """Graphite's _getPercentile (no interpolation): rank on the sorted
    non-null points (same math as the reference's percentile helpers)."""
    pts = np.sort(values[~np.isnan(values)])
    if len(pts) == 0:
        return np.nan
    fractional = (n / 100.0) * (len(pts) + 1)
    rank = int(fractional)
    if fractional - rank > 0:
        rank += 1
    rank = min(max(rank, 1), len(pts))
    return float(pts[rank - 1])


def _safe_stat(fn, values):
    with _quiet():
        out = fn(values)
    return out


@register("group")
def _group(eng, args, *_):
    return _flatten(args)


@register("identity")
def _identity(eng, args, start, end, step):
    grid = np.arange(start, end, step, dtype=np.int64)
    name = args[0] if args and isinstance(args[0], (str, bytes)) else "identity"
    name = name.encode() if isinstance(name, str) else name
    return [Series(name, grid, (grid // NS).astype(np.float64))]


@register("threshold")
def _threshold(eng, args, start, end, step):
    grid = np.arange(start, end, step, dtype=np.int64)
    label = args[1] if len(args) > 1 else str(args[0])
    return [Series(str(label).encode(), grid, np.full(len(grid), float(args[0])))]


@register("aliasSub")
def _alias_sub(eng, args, *_):
    series, search, replace = args[0], args[1], args[2]
    rx = re.compile(search.encode() if isinstance(search, str) else search)
    rep = replace.encode() if isinstance(replace, str) else replace
    # graphite uses \1 backrefs; python re.sub supports them directly
    return [Series(rx.sub(rep, s.name), s.times, s.values) for s in series]


@register("aliasByMetric")
def _alias_by_metric(eng, args, *_):
    return [Series(s.name.split(b".")[-1], s.times, s.values) for s in args[0]]


@register("substr")
def _substr(eng, args, *_):
    series = args[0]
    start_i = int(args[1]) if len(args) > 1 else 0
    stop_i = int(args[2]) if len(args) > 2 else 0
    out = []
    for s in series:
        parts = s.name.split(b".")
        sliced = parts[start_i:] if stop_i == 0 else parts[start_i:stop_i]
        out.append(Series(b".".join(sliced), s.times, s.values))
    return out


def _filter_series(series, stat_fn, pred):
    out = []
    for s in series:
        v = _safe_stat(stat_fn, s.values)
        if not np.isnan(v) and pred(v):
            out.append(s)
    return out


@register("averageBelow")
def _average_below(eng, args, *_):
    return _filter_series(args[0], np.nanmean, lambda v: v <= args[1])


@register("currentBelow")
def _current_below(eng, args, *_):
    def last(vals):
        ok = vals[~np.isnan(vals)]
        return ok[-1] if len(ok) else np.nan

    return _filter_series(args[0], last, lambda v: v <= args[1])


@register("maximumAbove")
def _maximum_above(eng, args, *_):
    return _filter_series(args[0], np.nanmax, lambda v: v > args[1])


@register("maximumBelow")
def _maximum_below(eng, args, *_):
    return _filter_series(args[0], np.nanmax, lambda v: v <= args[1])


@register("minimumAbove")
def _minimum_above(eng, args, *_):
    return _filter_series(args[0], np.nanmin, lambda v: v > args[1])


@register("minimumBelow")
def _minimum_below(eng, args, *_):
    return _filter_series(args[0], np.nanmin, lambda v: v <= args[1])


def _top_n(series, n, stat_fn, reverse):
    # all-NaN series must rank LAST in either direction
    sentinel = -np.inf if reverse else np.inf
    keyed = []
    for s in series:
        v = _safe_stat(stat_fn, s.values)
        keyed.append((v if not np.isnan(v) else sentinel, s))
    keyed.sort(key=lambda kv: kv[0], reverse=reverse)
    return [s for _, s in keyed[: int(n)]]


@register("highestAverage")
def _highest_average(eng, args, *_):
    return _top_n(args[0], args[1], np.nanmean, True)


@register("lowestAverage")
def _lowest_average(eng, args, *_):
    return _top_n(args[0], args[1], np.nanmean, False)


@register("highestMin")
def _highest_min(eng, args, *_):
    return _top_n(args[0], args[1], np.nanmin, True)


@register("lowestMax")
def _lowest_max(eng, args, *_):
    return _top_n(args[0], args[1], np.nanmax, False)


@register("sortByMinima")
def _sort_by_minima(eng, args, *_):
    with _quiet():
        return sorted(args[0], key=lambda s: _safe_stat(np.nanmin, s.values))


@register("sortByTotal")
def _sort_by_total(eng, args, *_):
    with _quiet():
        return sorted(args[0], key=lambda s: -_safe_stat(np.nansum, s.values))


def _window_points(arg, step: int) -> int:
    """Window argument -> point count: bare numbers are points, interval
    strings ('5min') are divided by the render step (graphite semantics)."""
    if isinstance(arg, str):
        return max(int(_parse_interval(arg) // step), 1)
    return max(int(arg), 1)


def _moving(series, window, fn):
    out = []
    for s in series:
        v = s.values
        acc = np.full(len(v), np.nan)
        for i in range(len(v)):
            lo = max(0, i - int(window) + 1)
            sel = v[lo : i + 1]
            if (~np.isnan(sel)).any():
                acc[i] = _safe_stat(fn, sel)
        out.append(Series(s.name, s.times, acc))
    return out


@register("movingMedian")
def _moving_median(eng, args, start, end, step):
    return _moving(args[0], _window_points(args[1], step), np.nanmedian)


@register("movingMax")
def _moving_max(eng, args, start, end, step):
    return _moving(args[0], _window_points(args[1], step), np.nanmax)


@register("movingMin")
def _moving_min(eng, args, start, end, step):
    return _moving(args[0], _window_points(args[1], step), np.nanmin)


@register("movingSum")
def _moving_sum(eng, args, start, end, step):
    return _moving(args[0], _window_points(args[1], step), np.nansum)


@register("stdev")
def _stdev(eng, args, *_):
    return _moving(args[0], args[1], np.nanstd)


@register("delay")
def _delay(eng, args, *_):
    series, steps = args[0], int(args[1])
    out = []
    for s in series:
        v = np.full(len(s.values), np.nan)
        if steps >= 0:
            v[steps:] = s.values[: len(v) - steps] if steps else s.values
        else:
            v[:steps] = s.values[-steps:]
        out.append(Series(s.name, s.times, v))
    return out


@register("changed")
def _changed(eng, args, *_):
    # graphite semantics: None points emit 0, and comparison is against
    # the LAST NON-NULL value (a change across a gap still counts)
    out = []
    for s in args[0]:
        v = s.values
        # forward-fill previous non-null value at each position
        idx = np.where(np.isnan(v), 0, np.arange(len(v)) + 1)
        np.maximum.accumulate(idx, out=idx)
        prev_nn = np.concatenate([[np.nan], np.where(idx[:-1] > 0,
                                                     v[np.maximum(idx[:-1] - 1, 0)],
                                                     np.nan)])
        ch = ((v != prev_nn) & ~np.isnan(v) & ~np.isnan(prev_nn)).astype(float)
        out.append(Series(s.name, s.times, ch))
    return out


@register("isNonNull")
def _is_non_null(eng, args, *_):
    return [Series(s.name, s.times, (~np.isnan(s.values)).astype(float))
            for s in args[0]]


@register("removeAboveValue")
def _remove_above_value(eng, args, *_):
    return [Series(s.name, s.times,
                   np.where(s.values > args[1], np.nan, s.values))
            for s in args[0]]


@register("removeBelowValue")
def _remove_below_value(eng, args, *_):
    return [Series(s.name, s.times,
                   np.where(s.values < args[1], np.nan, s.values))
            for s in args[0]]


@register("removeAbovePercentile")
def _remove_above_percentile(eng, args, *_):
    out = []
    for s in args[0]:
        p = _graphite_percentile(s.values, float(args[1]))
        out.append(Series(s.name, s.times,
                          np.where(s.values > p, np.nan, s.values)))
    return out


@register("removeBelowPercentile")
def _remove_below_percentile(eng, args, *_):
    out = []
    for s in args[0]:
        p = _graphite_percentile(s.values, float(args[1]))
        out.append(Series(s.name, s.times,
                          np.where(s.values < p, np.nan, s.values)))
    return out


@register("nPercentile")
def _n_percentile(eng, args, *_):
    out = []
    for s in args[0]:
        p = _graphite_percentile(s.values, float(args[1]))
        name = b"nPercentile(%s, %g)" % (s.name, float(args[1]))
        out.append(Series(name, s.times, np.full(len(s.values), p)))
    return out


@register("percentileOfSeries")
def _percentile_of_series(eng, args, *_):
    series, n = args[0], float(args[1])
    if not series:
        return []
    stack = np.stack([s.values for s in series])
    vals = np.array([_graphite_percentile(stack[:, i], n)
                     for i in range(stack.shape[1])])
    return [Series(b"percentileOfSeries(%s, %g)" % (series[0].name, n),
                   series[0].times, vals)]


@register("rangeOfSeries")
def _range_of_series(eng, args, *_):
    series = _flatten(args)
    with _quiet():
        return _combine(series, lambda st: np.nanmax(st, axis=0) - np.nanmin(st, axis=0),
                        b"rangeOfSeries")


@register("multiplySeries")
def _multiply_series(eng, args, *_):
    series = _flatten(args)
    with _quiet():
        return _combine(series, _nan_masked(lambda st: np.nanprod(st, axis=0)),
                        b"multiplySeries")


@register("stddevSeries")
def _stddev_series(eng, args, *_):
    with _quiet():
        return _combine(args[0], lambda st: np.nanstd(st, axis=0), b"stddevSeries")


@register("logarithm")
@register("log")
def _logarithm(eng, args, *_):
    base = float(args[1]) if len(args) > 1 else 10.0
    with _quiet():
        return [Series(s.name, s.times, np.log(s.values) / np.log(base))
                for s in args[0]]


@register("squareRoot")
def _square_root(eng, args, *_):
    with _quiet():
        return [Series(s.name, s.times, np.sqrt(s.values)) for s in args[0]]


@register("pow")
def _pow(eng, args, *_):
    with _quiet():
        return [Series(s.name, s.times, s.values ** float(args[1]))
                for s in args[0]]


@register("scaleToSeconds")
def _scale_to_seconds(eng, args, start, end, step):
    factor = float(args[1]) / (step / NS)
    return [Series(s.name, s.times, s.values * factor) for s in args[0]]


@register("consolidateBy")
@register("cumulative")
def _consolidate_by(eng, args, *_):
    # consolidation policy applies at render-resolution reduction, which
    # this engine performs at fetch; accepted for dashboard compatibility
    return args[0]


@register("drawAsInfinite")
@register("secondYAxis")
@register("stacked")
def _render_hint(eng, args, *_):
    # pure render-style hints: series pass through unchanged
    return args[0]


@register("averageSeriesWithWildcards")
def _average_series_with_wildcards(eng, args, *_):
    return _series_with_wildcards(args, np.nanmean)


@register("sumSeriesWithWildcards")
def _sum_series_with_wildcards(eng, args, *_):
    return _series_with_wildcards(args, np.nansum)


def _nan_masked(op):
    """All-NaN columns stay NaN (nansum/nanprod would fabricate 0/1)."""
    def apply(stack):
        out = op(stack)
        return np.where(np.isnan(stack).all(axis=0), np.nan, out)

    return apply


def _series_with_wildcards(args, op):
    series = args[0]
    positions = sorted(int(a) for a in args[1:])
    groups: dict[bytes, list] = {}
    for s in series:
        parts = [p for i, p in enumerate(s.name.split(b".")) if i not in positions]
        groups.setdefault(b".".join(parts), []).append(s)
    out = []
    with _quiet():
        for name, members in groups.items():
            combined = _combine(
                members, _nan_masked(lambda st: op(st, axis=0)), name)
            out.extend(combined)
    return out


@register("groupByNodes")
def _group_by_nodes(eng, args, *_):
    series, agg = args[0], args[1]
    nodes = [int(a) for a in args[2:]]
    op = {"sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
          "max": np.nanmax, "min": np.nanmin}[agg]
    groups: dict[bytes, list] = {}
    for s in series:
        parts = s.name.split(b".")
        key = b".".join(parts[n] for n in nodes if -len(parts) <= n < len(parts))
        groups.setdefault(key, []).append(s)
    out = []
    with _quiet():
        for name, members in groups.items():
            out.extend(_combine(
                members, _nan_masked(lambda st: op(st, axis=0)), name))
    return out


@register("weightedAverage")
def _weighted_average(eng, args, *_):
    avg_series, weight_series = args[0], args[1]
    nodes = [int(a) for a in args[2:]]

    def key(s):
        parts = s.name.split(b".")
        return b".".join(parts[n] for n in nodes if -len(parts) <= n < len(parts))

    weights = {key(s): s for s in weight_series}
    num = None
    den = None
    with _quiet():
        for s in avg_series:
            w = weights.get(key(s))
            if w is None:
                continue
            prod = s.values * w.values
            num = prod if num is None else np.nansum([num, prod], axis=0)
            den = w.values.copy() if den is None else np.nansum([den, w.values], axis=0)
        if num is None:
            return []
        vals = num / den
    return [Series(b"weightedAverage", avg_series[0].times, vals)]


@register("mostDeviant")
def _most_deviant(eng, args, *_):
    series, n = args[0], int(args[1])
    with _quiet():
        keyed = sorted(
            series,
            key=lambda s: -(np.nanstd(s.values) if (~np.isnan(s.values)).any()
                            else -np.inf),
        )
    return keyed[:n]


@register("linearRegression")
def _linear_regression(eng, args, *_):
    out = []
    for s in args[0]:
        v = s.values
        ok = ~np.isnan(v)
        if ok.sum() < 2:
            out.append(s)
            continue
        x = (s.times / NS).astype(np.float64)
        slope, intercept = np.polyfit(x[ok], v[ok], 1)
        out.append(Series(s.name, s.times, slope * x + intercept))
    return out


@register("averageOutsidePercentile")
def _average_outside_percentile(eng, args, *_):
    series, n = args[0], float(args[1])
    n = max(n, 100.0 - n)
    with _quiet():
        avgs = [_safe_stat(np.nanmean, s.values) for s in series]
    lo = _graphite_percentile(np.asarray(avgs, float), 100.0 - n)
    hi = _graphite_percentile(np.asarray(avgs, float), n)
    return [s for s, a in zip(series, avgs) if not (lo < a < hi)]


# ---------------------------------------------------------------------------
# remainder of the reference's builtin set: aggregate family, Holt-Winters,
# moving windows, time/interval utilities
# (query/graphite/native/builtin_functions.go:2841-3058)
# ---------------------------------------------------------------------------

_INTERVAL_UNITS = {
    "s": NS, "sec": NS, "second": NS, "seconds": NS,
    "min": 60 * NS, "minute": 60 * NS, "minutes": 60 * NS,
    "h": 3600 * NS, "hour": 3600 * NS, "hours": 3600 * NS,
    "d": 86400 * NS, "day": 86400 * NS, "days": 86400 * NS,
    "w": 7 * 86400 * NS, "week": 7 * 86400 * NS, "weeks": 7 * 86400 * NS,
    "mon": 30 * 86400 * NS, "month": 30 * 86400 * NS, "months": 30 * 86400 * NS,
    "y": 365 * 86400 * NS, "year": 365 * 86400 * NS, "years": 365 * 86400 * NS,
}

_INTERVAL_RE = re.compile(r"(\d+)\s*([A-Za-z]+)")


def _parse_interval(spec) -> int:
    """Graphite interval ('10s', '1min', '1hour', '7d') -> ns; negative
    sign allowed ('-1h' -> -3600s)."""
    if isinstance(spec, (int, float)):
        return int(spec * NS)  # bare numbers are seconds
    s = spec.strip()
    sign = -1 if s.startswith("-") else 1
    s = s.lstrip("+-")
    total, pos = 0, 0
    for m in _INTERVAL_RE.finditer(s):
        if m.start() != pos:
            raise GraphiteError(f"invalid interval {spec!r}")
        unit = m.group(2).lower()
        if unit not in _INTERVAL_UNITS:
            raise GraphiteError(f"invalid interval unit {spec!r}")
        total += int(m.group(1)) * _INTERVAL_UNITS[unit]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise GraphiteError(f"invalid interval {spec!r}")
    return sign * total


_AGG_BY_NAME = {
    "average": np.nanmean, "avg": np.nanmean, "mean": np.nanmean,
    "sum": np.nansum, "total": np.nansum,
    "min": np.nanmin, "minimum": np.nanmin,
    "max": np.nanmax, "maximum": np.nanmax,
    "median": np.nanmedian,
    "stddev": np.nanstd, "stdev": np.nanstd,
    "count": lambda v, **kw: (~np.isnan(np.asarray(v))).sum(**{
        k: v2 for k, v2 in kw.items() if k == "axis"}),
    "range": lambda v, **kw: np.nanmax(v, **kw) - np.nanmin(v, **kw),
    "rangeOf": lambda v, **kw: np.nanmax(v, **kw) - np.nanmin(v, **kw),
    "multiply": np.nanprod,
    # graphite safeDiff: first minus the sum of the rest
    "diff": lambda v, **kw: (np.asarray(v)[0] - np.nansum(np.asarray(v)[1:], **kw)
                             if len(np.asarray(v)) else np.nan),
    "last": lambda v, **kw: _last_stat(np.asarray(v), **kw),
    "current": lambda v, **kw: _last_stat(np.asarray(v), **kw),
}


def _last_stat(v, axis=None):
    """Last non-NaN value (per row when axis=0 over a [S, T] stack)."""
    if v.ndim == 1:
        ok = ~np.isnan(v)
        return v[np.where(ok)[0][-1]] if ok.any() else np.nan
    out = np.full(v.shape[1], np.nan)
    for j in range(v.shape[1]):
        col = v[:, j]
        ok = ~np.isnan(col)
        if ok.any():
            out[j] = col[np.where(ok)[0][-1]]
    return out


def _agg_op(name: str):
    op = _AGG_BY_NAME.get(name)
    if op is None:
        raise GraphiteError(f"unknown aggregation function {name!r}")
    return op


def _series_stat(name: str, s: Series) -> float:
    return _safe_stat(lambda v: _agg_op(name)(v), s.values)


@register("aggregate")
def _aggregate(eng, args, *_):
    series, func = _flatten(args[:1]), args[1]
    op = _agg_op(func)
    name = f"{func}Series".encode() + b"(" + b",".join(s.name for s in series) + b")"
    with _quiet():
        return _combine(series, _nan_masked(lambda st: op(st, axis=0)), name)


@register("aggregateLine")
def _aggregate_line(eng, args, start, end, step):
    series = args[0]
    func = args[1] if len(args) > 1 else "average"
    grid = np.arange(start, end, step, dtype=np.int64)
    out = []
    for s in series:
        v = _series_stat(func, s)
        name = b"aggregateLine(" + s.name + f",{v:g})".encode()
        out.append(Series(name, grid, np.full(len(grid), v)))
    return out


@register("aggregateWithWildcards")
def _aggregate_with_wildcards(eng, args, *_):
    series, func = args[0], args[1]
    op = _agg_op(func)
    return _series_with_wildcards([series] + list(args[2:]),
                                  lambda st, axis=0: op(st, axis=axis))


@register("multiplySeriesWithWildcards")
def _multiply_series_with_wildcards(eng, args, *_):
    return _series_with_wildcards(args, np.nanprod)


@register("applyByNode")
def _apply_by_node(eng, args, start, end, step):
    """Groups series by their first node+1 path nodes and evaluates the
    template (with % replaced by the prefix) once per group."""
    series, node, template = args[0], int(args[1]), args[2]
    new_name = args[3] if len(args) > 3 else None
    prefixes = []
    for s in series:
        prefix = b".".join(s.name.split(b".")[: node + 1]).decode()
        if prefix not in prefixes:
            prefixes.append(prefix)
    out = []
    for prefix in prefixes:
        ast, pos = parse_target(template.replace("%", prefix))
        got = eng._eval(ast, start, end, step)
        if isinstance(got, list):
            for g in got:
                name = new_name.replace("%", prefix).encode() if new_name else g.name
                out.append(Series(name, g.times, g.values))
    return out


@register("cactiStyle")
def _cacti_style(eng, args, *_):
    out = []
    for s in args[0]:
        cur = _series_stat("last", s)
        mx = _series_stat("max", s)
        mn = _series_stat("min", s)
        name = s.name + f" Current:{cur:g} Max:{mx:g} Min:{mn:g}".encode()
        out.append(Series(name, s.times, s.values))
    return out


@register("dashed")
def _dashed(eng, args, *_):
    length = args[1] if len(args) > 1 else 5.0
    return [
        Series(b"dashed(" + s.name + f",{length:g})".encode(), s.times, s.values)
        for s in args[0]
    ]


@register("divideSeriesLists")
def _divide_series_lists(eng, args, *_):
    dividends, divisors = args[0], args[1]
    if len(dividends) != len(divisors):
        raise GraphiteError("divideSeriesLists: list lengths differ")
    out = []
    with _quiet():
        for a, b in zip(dividends, divisors):
            v = np.where(b.values == 0, np.nan, a.values / b.values)
            out.append(Series(b"divideSeries(" + a.name + b"," + b.name + b")",
                              a.times, v))
    return out


@register("powSeries")
def _pow_series(eng, args, *_):
    series = _flatten(args)
    if not series:
        return []
    with _quiet():
        acc = series[0].values.copy()
        for s in series[1:]:
            acc = np.power(acc, s.values)
    name = b"powSeries(" + b",".join(s.name for s in series) + b")"
    return [Series(name, series[0].times, acc)]


@register("exponentialMovingAverage")
def _exponential_moving_average(eng, args, start, end, step):
    series, window = args[0], _window_points(args[1], step)
    alpha = 2.0 / (window + 1)
    out = []
    for s in series:
        v = s.values
        ema = np.full(len(v), np.nan)
        acc = None
        for i in range(len(v)):
            x = v[i]
            if np.isnan(x):
                ema[i] = acc if acc is not None else np.nan
                continue
            acc = x if acc is None else alpha * x + (1 - alpha) * acc
            ema[i] = acc
        out.append(Series(b"ema(" + s.name + f",{window})".encode(),
                          s.times, ema))
    return out


@register("fallbackSeries")
def _fallback_series(eng, args, *_):
    return args[0] if args[0] else args[1]


_FILTER_OPS = {
    ">": np.greater, ">=": np.greater_equal, "<": np.less,
    "<=": np.less_equal, "=": np.equal, "!=": np.not_equal,
}


@register("filterSeries")
def _filter_series_builtin(eng, args, *_):
    series, func, operator, threshold = args[0], args[1], args[2], float(args[3])
    cmp = _FILTER_OPS.get(operator)
    if cmp is None:
        raise GraphiteError(f"unknown operator {operator!r}")
    return [s for s in series if cmp(_series_stat(func, s), threshold)]


@register("highest")
def _highest(eng, args, *_):
    series = args[0]
    n = int(args[1]) if len(args) > 1 else 1
    func = args[2] if len(args) > 2 else "average"
    ranked = sorted(series, key=lambda s: -_nan_low(_series_stat(func, s)))
    return ranked[:n]


@register("lowest")
def _lowest(eng, args, *_):
    series = args[0]
    n = int(args[1]) if len(args) > 1 else 1
    func = args[2] if len(args) > 2 else "average"
    ranked = sorted(series, key=lambda s: _nan_high(_series_stat(func, s)))
    return ranked[:n]


@register("sortBy")
def _sort_by(eng, args, *_):
    series = args[0]
    func = args[1] if len(args) > 1 else "average"
    reverse = len(args) > 2 and _truthy(args[2])
    return sorted(series, key=lambda s: _nan_high(_series_stat(func, s)),
                  reverse=reverse)


def _truthy(arg) -> bool:
    if isinstance(arg, str):
        return arg.lower() in ("true", "1")
    return bool(arg)


def _nan_low(x: float) -> float:
    return -np.inf if np.isnan(x) else x


def _nan_high(x: float) -> float:
    return np.inf if np.isnan(x) else x


@register("hitcount")
def _hitcount(eng, args, start, end, step):
    """Rate (hits/sec) -> hit counts per interval bucket: sum(v * step_s)."""
    series, interval = args[0], _parse_interval(args[1])
    out = []
    step_s = step / NS
    for s in series:
        if not len(s.times):
            out.append(s)
            continue
        bucket = ((s.times - s.times[0]) // interval).astype(np.int64)
        n_buckets = int(bucket[-1]) + 1
        times = s.times[0] + np.arange(n_buckets) * interval
        vals = np.full(n_buckets, np.nan)
        with _quiet():
            for b in range(n_buckets):
                sel = s.values[bucket == b]
                if (~np.isnan(sel)).any():
                    vals[b] = np.nansum(sel) * step_s
        name = b"hitcount(" + s.name + b",'" + str(args[1]).encode() + b"')"
        out.append(Series(name, times, vals))
    return out


@register("smartSummarize")
def _smart_summarize(eng, args, start, end, step):
    """summarize() aligned to the render start (no bucket offset drift)."""
    series, interval = args[0], _parse_interval(args[1])
    func = args[2] if len(args) > 2 else "sum"
    op = _agg_op(func)
    out = []
    for s in series:
        bucket = ((s.times - start) // interval).astype(np.int64)
        n_buckets = int(bucket[-1]) + 1 if len(bucket) else 0
        times = start + np.arange(n_buckets) * interval
        vals = np.full(n_buckets, np.nan)
        with _quiet():
            for b in range(n_buckets):
                sel = s.values[bucket == b]
                if (~np.isnan(sel)).any():
                    vals[b] = op(sel)
        name = (b"smartSummarize(" + s.name + b",'"
                + str(args[1]).encode() + b"','" + func.encode() + b"')")
        out.append(Series(name, times, vals))
    return out


@register("integralByInterval")
def _integral_by_interval(eng, args, start, end, step):
    """Cumulative sum resetting at each interval boundary."""
    series, interval = args[0], _parse_interval(args[1])
    out = []
    for s in series:
        v = np.where(np.isnan(s.values), 0.0, s.values)
        bucket = ((s.times - start) // interval).astype(np.int64)
        acc = np.cumsum(v)
        if len(v):
            # subtract the running total as of each bucket's first point
            is_first = np.concatenate([[True], bucket[1:] != bucket[:-1]])
            base = np.where(is_first, acc - v, -np.inf)
            np.maximum.accumulate(base, out=base)
            acc = acc - base
        out.append(Series(b"integralByInterval(" + s.name + b")", s.times, acc))
    return out


@register("interpolate")
def _interpolate(eng, args, *_):
    series = args[0]
    limit = int(args[1]) if len(args) > 1 else None
    out = []
    for s in series:
        v = s.values.copy()
        ok = ~np.isnan(v)
        if ok.sum() >= 2:
            idx = np.arange(len(v))
            gaps = np.interp(idx, idx[ok], v[ok])
            fill = ~ok
            # leading/trailing NaN stay NaN (interp would clamp)
            fill &= (idx >= idx[ok][0]) & (idx <= idx[ok][-1])
            if limit is not None:
                # only fill gaps of at most `limit` consecutive NaNs
                run = np.zeros(len(v), dtype=np.int64)
                count = 0
                for i in range(len(v)):
                    count = count + 1 if not ok[i] else 0
                    run[i] = count
                total = np.zeros(len(v), dtype=np.int64)
                for i in range(len(v) - 1, -1, -1):
                    total[i] = run[i] if (i == len(v) - 1 or run[i + 1] == 0) \
                        else total[i + 1]
                    if run[i] == 0:
                        total[i] = 0
                fill &= np.array([total[i] <= limit or ok[i]
                                  for i in range(len(v))])
            v[fill] = gaps[fill]
        out.append(Series(s.name, s.times, v))
    return out


@register("legendValue")
def _legend_value(eng, args, *_):
    series, types = args[0], [a for a in args[1:] if isinstance(a, str)]
    out = []
    for s in series:
        name = s.name
        for t in types:
            name += f" ({t}: {_series_stat(t, s):g})".encode()
        out.append(Series(name, s.times, s.values))
    return out


@register("movingWindow")
def _moving_window(eng, args, start, end, step):
    series, window = args[0], _window_points(args[1], step)
    func = args[2] if len(args) > 2 else "average"
    op = _agg_op(func)
    out = []
    for s in _moving(series, window, op):
        name = (b"movingWindow(" + s.name
                + f",{window},'{func}')".encode())
        out.append(Series(name, s.times, s.values))
    return out


@register("offsetToZero")
def _offset_to_zero(eng, args, *_):
    out = []
    for s in args[0]:
        m = _safe_stat(np.nanmin, s.values)
        out.append(Series(b"offsetToZero(" + s.name + b")", s.times,
                          s.values - m))
    return out


@register("randomWalk")
@register("randomWalkFunction")
def _random_walk(eng, args, start, end, step):
    """Deterministic per name (seeded by it), so renders are reproducible."""
    name = args[0] if args and isinstance(args[0], str) else "randomWalk"
    grid = np.arange(start, end, step, dtype=np.int64)
    rng = np.random.default_rng(zlib.adler32(name.encode()))
    steps = rng.random(len(grid)) - 0.5
    return [Series(name.encode(), grid, np.cumsum(steps))]


@register("removeEmptySeries")
def _remove_empty_series(eng, args, *_):
    series = args[0]
    x_files_factor = float(args[1]) if len(args) > 1 else 0.0
    out = []
    for s in series:
        frac = (~np.isnan(s.values)).mean() if len(s.values) else 0.0
        if frac > 0 and frac >= x_files_factor:
            out.append(s)
    return out


@register("round")
@register("roundFunction")
def _round(eng, args, *_):
    precision = int(args[1]) if len(args) > 1 else 0
    return [
        Series(s.name, s.times, np.round(s.values, precision))
        for s in args[0]
    ]


@register("sustainedAbove")
def _sustained_above(eng, args, start, end, step):
    return _sustained(args, step, above=True)


@register("sustainedBelow")
def _sustained_below(eng, args, start, end, step):
    return _sustained(args, step, above=False)


def _sustained(args, step, above: bool):
    """Keep only values that stayed above/below the threshold for at least
    the interval; everything else becomes NaN."""
    series, value, interval = args[0], float(args[1]), _parse_interval(args[2])
    min_run = max(int(interval // step), 1)
    out = []
    for s in series:
        v = s.values
        with _quiet():
            cond = (v > value) if above else (v < value)
        cond = np.where(np.isnan(v), False, cond)
        keep = np.zeros(len(v), dtype=bool)
        i = 0
        while i < len(v):
            if cond[i]:
                j = i
                while j < len(v) and cond[j]:
                    j += 1
                if j - i >= min_run:
                    keep[i:j] = True
                i = j
            else:
                i += 1
        tag = b"sustainedAbove" if above else b"sustainedBelow"
        out.append(Series(tag + b"(" + s.name + f",{value:g})".encode(),
                          s.times, np.where(keep, v, np.nan)))
    return out


@register("time")
@register("timeFunction")
def _time_fn(eng, args, start, end, step):
    name = args[0] if args and isinstance(args[0], str) else "time"
    step_override = int(args[1]) * NS if len(args) > 1 else step
    grid = np.arange(start, end, step_override, dtype=np.int64)
    return [Series(name.encode(), grid, (grid / NS).astype(np.float64))]


@register("timeSlice")
def _time_slice(eng, args, start, end, step):
    """NaN outside the sliced window. Interval-string bounds are relative
    to the render END (graphite resolves them against 'now'): '-3min' means
    3 minutes before the end of the window. Numbers are epoch seconds."""
    series = args[0]
    lo = _slice_bound(args[1], start, end) if len(args) > 1 else start
    hi = _slice_bound(args[2], start, end) if len(args) > 2 else end
    out = []
    for s in series:
        sel = (s.times >= lo) & (s.times < hi)
        out.append(Series(b"timeSlice(" + s.name + b")", s.times,
                          np.where(sel, s.values, np.nan)))
    return out


def _slice_bound(arg, start, end) -> int:
    """Interval strings resolve against the render end ('now'); bare
    numbers are absolute epoch seconds."""
    if isinstance(arg, str):
        if arg == "now":
            return end
        return end + _parse_interval(arg)
    return int(arg) * NS


@register("useSeriesAbove")
def _use_series_above(eng, args, start, end, step):
    """For series whose max exceeds value, fetch the search->replace
    renamed metric instead (reference example: reqs -> time)."""
    series, value, search, replace = (
        args[0], float(args[1]), args[2], args[3])
    out = []
    for s in series:
        if _nan_low(_series_stat("max", s)) > value:
            pattern = s.name.decode().replace(search, replace)
            out.extend(eng.fetch(pattern, start, end, step))
    return out


# -- Holt-Winters (triple exponential smoothing, daily season; the
#    reference's implementation follows graphite-web's, which bootstraps
#    with 7 days of history — here the visible window itself bootstraps,
#    and a window shorter than two seasons degrades to non-seasonal
#    double smoothing. graphite/native/holt_winters.go role) --

_HW_ALPHA, _HW_BETA, _HW_GAMMA = 0.1, 0.0035, 0.1


def _holt_winters_analysis(v: np.ndarray, season_len: int):
    n = len(v)
    forecast = np.full(n, np.nan)
    deviation = np.full(n, np.nan)
    intercept = 0.0
    slope = 0.0
    seasonal = np.zeros(max(season_len, 1))
    dev = np.zeros(max(season_len, 1))
    seasonal_ok = season_len >= 1 and n >= 2 * season_len
    started = False
    for i in range(n):
        x = v[i]
        if np.isnan(x):
            forecast[i] = intercept + slope + (seasonal[i % season_len]
                                               if seasonal_ok else 0.0)
            deviation[i] = dev[i % season_len] if seasonal_ok else 0.0
            continue
        if not started:
            intercept, slope, started = x, 0.0, True
            forecast[i] = x
            deviation[i] = 0.0
            continue
        s_idx = i % season_len if seasonal_ok else 0
        last_seasonal = seasonal[s_idx]
        pred = intercept + slope + (last_seasonal if seasonal_ok else 0.0)
        forecast[i] = pred
        prev_intercept, prev_slope = intercept, slope
        if seasonal_ok:
            intercept = (_HW_ALPHA * (x - last_seasonal)
                         + (1 - _HW_ALPHA) * (prev_intercept + prev_slope))
            seasonal[s_idx] = (_HW_GAMMA * (x - intercept)
                               + (1 - _HW_GAMMA) * last_seasonal)
        else:
            intercept = _HW_ALPHA * x + (1 - _HW_ALPHA) * (prev_intercept + prev_slope)
        slope = _HW_BETA * (intercept - prev_intercept) + (1 - _HW_BETA) * prev_slope
        dev[s_idx] = (_HW_GAMMA * abs(x - pred)
                      + (1 - _HW_GAMMA) * dev[s_idx])
        deviation[i] = dev[s_idx]
    return forecast, deviation


def _hw_season_len(s: Series, step: int) -> int:
    return max(int(86400 * NS // step), 1)


@register("holtWintersForecast")
def _holt_winters_forecast(eng, args, start, end, step):
    out = []
    for s in args[0]:
        forecast, _ = _holt_winters_analysis(s.values, _hw_season_len(s, step))
        out.append(Series(b"holtWintersForecast(" + s.name + b")",
                          s.times, forecast))
    return out


@register("holtWintersConfidenceBands")
def _holt_winters_confidence_bands(eng, args, start, end, step):
    delta = float(args[1]) if len(args) > 1 else 3.0
    out = []
    for s in args[0]:
        forecast, deviation = _holt_winters_analysis(
            s.values, _hw_season_len(s, step))
        out.append(Series(b"holtWintersConfidenceUpper(" + s.name + b")",
                          s.times, forecast + delta * deviation))
        out.append(Series(b"holtWintersConfidenceLower(" + s.name + b")",
                          s.times, forecast - delta * deviation))
    return out


@register("holtWintersAberration")
def _holt_winters_aberration(eng, args, start, end, step):
    delta = float(args[1]) if len(args) > 1 else 3.0
    out = []
    for s in args[0]:
        forecast, deviation = _holt_winters_analysis(
            s.values, _hw_season_len(s, step))
        upper = forecast + delta * deviation
        lower = forecast - delta * deviation
        with _quiet():
            ab = np.where(s.values > upper, s.values - upper,
                          np.where(s.values < lower, s.values - lower, 0.0))
        ab = np.where(np.isnan(s.values), np.nan, ab)
        out.append(Series(b"holtWintersAberration(" + s.name + b")",
                          s.times, ab))
    return out
