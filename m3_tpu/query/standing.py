"""Standing-query engine: incremental recording-rule evaluation.

The standing plane (ROADMAP #2) turns recording rules into CONTINUOUS
queries: each rule's PromQL expression compiles through the SAME
query/compiler.py plan path as an ad-hoc request (one fused jit program
per plan signature via the lru_cache program factory; the bounded plan
cache keys evaluations like any query), but evaluation is INCREMENTAL
per ingest batch instead of per request:

- every evaluation is keyed by the hot tier's fetch identity —
  ``(Namespace.data_version(), selector matchers, evaluation grid)`` —
  the exact key the compiled path uses for device-resident prepared
  slabs (storage/hottier.py). An unchanged key means the inputs cannot
  have changed: the rule is SKIPPED without touching storage.
- a changed namespace version is refined to shard granularity:
  ``Shard.data_version`` bumps tell the evaluator precisely WHICH
  shards' content moved, and a rule re-evaluates only when a bumped
  shard holds (or just received) series its selectors match. The
  matched-shard set comes from a cheap index probe (query_ids — no
  sample reads), so a steady-state batch re-evaluates only the rules it
  invalidated; everything else is counted ``rules_skipped``.
- a skipped rule emits no new output points; readers' lookback carries
  its last written value forward exactly as it would for the untouched
  input series, so skipping is value-preserving for staleness-bounded
  reads.

Output lands through the downsampler's per-policy write leg: the
policy's aggregated namespace (coarse resolution, long retention — what
cheapest-tier read resolution serves) and, by default, the unaggregated
namespace so fine-step reads inside raw retention see the outputs too.

Hosting: the aggregator's flush loop (aggregator/downsample.Downsampler
.flush) drives ``evaluate`` under the same leader/local-flush
discipline as aggregation output.
"""

from __future__ import annotations

import time

import numpy as np

from m3_tpu.query import promql
from m3_tpu.query.promql import Expr, VectorSelector

NS = 1_000_000_000

# catch-up bound: one evaluation never back-fills more than this many
# grid points (a stalled evaluator resumes bounded, not unbounded)
MAX_POINTS_PER_EVAL = 4096


def collect_selectors(e: Expr) -> list[VectorSelector]:
    """Every VectorSelector in the expression tree — the rule's input
    surface (what the invalidation probe matches against shards)."""
    out: list[VectorSelector] = []
    if isinstance(e, VectorSelector):
        out.append(e)
    for attr in ("expr", "selector", "lhs", "rhs", "param"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            out.extend(collect_selectors(child))
    for child in getattr(e, "args", ()) or ():
        if isinstance(child, Expr):
            out.extend(collect_selectors(child))
    return out


def _matcher_fp(selectors) -> tuple:
    """Stable fingerprint of every selector's matchers (the `selector`
    leg of the (data_version, selector, grid) evaluation key)."""
    return tuple(
        tuple(sorted((m.name, getattr(m.match_type, "value",
                                      str(m.match_type)), m.value)
                     for m in sel.matchers))
        for sel in selectors
    )


class _RuleState:
    """Per-rule incremental-evaluation bookkeeping."""

    __slots__ = ("selectors", "matcher_fp", "last_end", "shards", "key",
                 "evals", "skips", "last_error")

    def __init__(self, selectors):
        self.selectors = selectors
        self.matcher_fp = _matcher_fp(selectors)
        self.last_end = 0          # last evaluated grid point (ns)
        self.shards: set[int] = set()  # shards holding matched series
        self.key = None            # (data_version, selector, grid) id
        self.evals = 0
        self.skips = 0
        self.last_error: str | None = None


class StandingEvaluator:
    """Evaluates a set of StandingRules incrementally against one source
    namespace, writing outputs through the downsampler's namespace leg."""

    def __init__(self, db, rules, source_namespace: str = "default",
                 namespace_for=None, now_fn=None,
                 buffer_past_ns: int = 0, catchup_points: int = 2,
                 query_compile: bool = True, write_raw_namespace=None):
        from m3_tpu.query.engine import Engine
        from m3_tpu.utils.instrument import default_registry

        self.db = db
        self.source = source_namespace
        # rules always read the RAW tier: their own outputs must never
        # become their inputs through cheapest-tier resolution
        self.engine = Engine(db, source_namespace, resolve_tiers=False,
                             query_compile=query_compile, now_fn=now_fn)
        self.namespace_for = namespace_for  # StoragePolicy -> ns name
        self.now_fn = now_fn or time.time_ns
        self.buffer_past_ns = buffer_past_ns
        self.catchup_points = max(1, catchup_points)
        self.write_raw_namespace = (write_raw_namespace
                                    if write_raw_namespace is not None
                                    else source_namespace)
        self._scope = default_registry().root_scope("aggregator").subscope(
            "standing")
        self._states: dict[str, _RuleState] = {}
        self._rules: list = []
        self._last_shard_versions: dict[int, int] = {}
        self._last_placement_epoch: int | None = None
        # local mirrors of the registry counters (test + /debug surface)
        self.counts = {"evaluated": 0, "invalidated": 0, "skipped": 0,
                       "errors": 0}
        self.last_invalidated: set[str] = set()
        self.set_rules(rules)

    def set_rules(self, rules) -> None:
        """Swap the live rule list (KV reload); state for surviving rule
        names is kept so a reload does not force a full re-evaluation."""
        self._rules = list(rules)
        keep = {r.name for r in self._rules}
        self._states = {n: s for n, s in self._states.items() if n in keep}

    # -- input versioning ---------------------------------------------------

    def _source_ns(self):
        try:
            ns = self.db.namespaces[self.source]
        except Exception:  # noqa: BLE001 - facade without the map
            return None
        # same capability marker as the engine's fetch key: facades have
        # no local version truth, so incremental skip cannot apply
        if not getattr(ns, "has_version_truth", False):
            return None
        return ns

    def _shard_versions(self, ns) -> dict[int, int]:
        return {sid: s.data_version for sid, s in list(ns.shards.items())}

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, now_ns: int | None = None) -> dict:
        """One incremental pass over every rule; returns the pass
        summary {evaluated, invalidated, skipped, errors, points}."""
        now_ns = now_ns if now_ns is not None else self.now_fn()
        ns = self._source_ns()
        summary = {"evaluated": 0, "invalidated": 0, "skipped": 0,
                   "errors": 0, "points": 0}
        self.last_invalidated = set()
        if ns is not None:
            versions = self._shard_versions(ns)
            bumped = {sid for sid, v in versions.items()
                      if self._last_shard_versions.get(sid) != v}
            bumped |= set(self._last_shard_versions) - set(versions)
            epoch = ns._placement_epoch
            if self._last_placement_epoch != epoch:
                # shards moved: version sums alias across placements, so
                # every cached shard set is suspect — probe everything
                bumped |= set(versions) | {
                    s for st in self._states.values() for s in st.shards}
            self._last_shard_versions = versions
            self._last_placement_epoch = epoch
            ns_version = ns.data_version()
        else:
            bumped = None  # no local truth: every rule re-evaluates
            ns_version = None
        for rule in self._rules:
            self._evaluate_rule(rule, ns, ns_version, bumped, now_ns,
                                summary)
        for k in ("evaluated", "invalidated", "skipped", "errors"):
            if summary[k]:
                self._scope.counter(f"rules_{k}", summary[k])
                self.counts[k] += summary[k]
        return summary

    def _evaluate_rule(self, rule, ns, ns_version, bumped, now_ns: int,
                       summary: dict) -> None:
        state = self._states.get(rule.name)
        if state is None:
            try:
                selectors = collect_selectors(promql.parse(rule.expr))
            except Exception as e:  # noqa: BLE001 - out-of-band bad expr
                # (the KV store validates; only a bypassing writer lands
                # here) must not kill the flush loop — the rule keeps a
                # state slot so /debug shows its error, and retries next
                # flush (last_end stays 0 -> bootstrap)
                summary["errors"] += 1
                self._states.setdefault(rule.name, _RuleState([]))
                self._record_error(rule.name, str(e))
                return
            state = self._states[rule.name] = _RuleState(selectors)
        res = rule.policy.resolution_ns
        watermark = ((now_ns - self.buffer_past_ns) // res) * res
        if watermark <= 0:
            return
        # the hot tier's evaluation identity: (data_version, selector,
        # grid) — unchanged means the inputs and the requested grid are
        # byte-identical to the last pass, skip without touching storage
        key = (ns_version, state.matcher_fp, watermark, res)
        invalid, reason = self._invalidation(state, ns, bumped, watermark,
                                             key)
        if not invalid:
            state.skips += 1
            summary["skipped"] += 1
            return
        self.last_invalidated.add(rule.name)
        summary["invalidated"] += 1
        prev_end = state.last_end
        lag_s = (now_ns - (prev_end if prev_end else watermark)) / 1e9
        self._scope.observe("rule_eval_lag_seconds", max(0.0, lag_s))
        if prev_end:
            # re-evaluate the last emitted point too: a late write lands
            # in the current window and last-write-wins absorbs the
            # overwrite downstream
            start_pt = prev_end
        else:
            start_pt = watermark - (self.catchup_points - 1) * res
        start_pt = max(start_pt, res,
                       watermark - (MAX_POINTS_PER_EVAL - 1) * res)
        try:
            points = self._run(rule, state, ns, start_pt, watermark, res)
        except Exception as e:  # noqa: BLE001 - one broken rule must not
            # starve the rest of the flush
            summary["errors"] += 1
            self._record_error(rule.name, str(e))
            return
        state.last_end = watermark
        state.key = key
        state.evals += 1
        state.last_error = None
        if ns is not None:
            self._probe_shards(state, ns, start_pt, watermark)
        summary["evaluated"] += 1
        summary["points"] += points

    def _invalidation(self, state, ns, bumped, watermark: int, key):
        """(invalid?, reason). Exactness contract (pinned by tests): a
        batch touching shard S invalidates exactly the rules whose
        selectors match series now living in S."""
        if state.last_end == 0:
            return True, "bootstrap"
        if key == state.key:
            return False, "identity_unchanged"
        if ns is None or bumped is None:
            return True, "no_version_truth"
        if not bumped:
            return False, "unchanged"
        if state.shards & bumped:
            return True, "shard_version"
        # content moved somewhere this rule never matched — but a NEW
        # matching series may have landed there: one index probe (no
        # sample reads) refreshes the matched-shard set exactly
        self._probe_shards(state, ns, state.last_end, watermark)
        if state.shards & bumped:
            return True, "new_series"
        return False, "unchanged"

    def _probe_shards(self, state, ns, start_pt: int, end_pt: int) -> None:
        """Refresh the rule's matched-shard set from the index: matched
        series ids route to shards in one vectorized lookup."""
        from m3_tpu.index.query import matchers_to_query

        t_lo = start_pt - self.engine.lookback_ns
        t_hi = end_pt + 1
        shards: set[int] = set()
        for sel in state.selectors:
            docs = ns.query_ids(matchers_to_query(sel.matchers), t_lo, t_hi)
            ids = [d.series_id for d in docs]
            if ids:
                shards.update(
                    int(s) for s in ns.shard_set.lookup_many(ids))
        state.shards = shards

    def _run(self, rule, state, ns, start_pt: int, end_pt: int,
             res: int) -> int:
        """Evaluate the rule over [start_pt, end_pt] on its grid and
        write the outputs. The engine call compiles through
        query/compiler.py exactly like an ad-hoc query — one fused
        program per plan signature, plan-cache keyed — so a thousand
        flushes of the same rule trace and compile once."""
        from m3_tpu.query.engine import Vector

        expr = promql.parse(rule.expr)
        out, eval_ts = self.engine.query_range_expr(
            expr, int(start_pt), int(end_pt), int(res),
            query_text=f"standing:{rule.name}")
        if not isinstance(out, Vector) or not len(out.labels):
            return 0
        name = rule.name.encode()
        extra = dict(rule.labels)
        entries = []
        for li, lab in enumerate(out.labels):
            tags = {k: v for k, v in lab.items() if k != b"__name__"}
            tags.update(extra)
            tag_items = sorted(tags.items())
            row = out.values[li]
            ok = ~np.isnan(row)
            for ti in np.nonzero(ok)[0]:
                entries.append((name, tag_items, int(eval_ts[ti]),
                                float(row[ti])))
        if not entries:
            return 0
        out_ns = (self.namespace_for(rule.policy) if self.namespace_for
                  else rule.policy.namespace_name)
        self._write_outputs(out_ns, entries)
        if rule.write_raw and self.write_raw_namespace:
            self._write_outputs(self.write_raw_namespace, entries)
            if ns is not None:
                self._absorb_self_writes(ns, entries)
        return len(entries)

    def _write_outputs(self, namespace: str, entries) -> None:
        """Output writes are acked-or-retried: both write_batch surfaces
        (Database and the quorum ClusterDatabase facade) report per-entry
        failures as aligned strings instead of raising, so a partially
        dropped batch must fail the pass HERE — otherwise the watermark
        advances past grid points that never landed and the standing
        output silently loses them (no later flush re-covers the window)."""
        results = self.db.write_batch(namespace, entries)
        bad = [r for r in results or () if r is not None]
        if bad:
            raise RuntimeError(
                f"standing output write to {namespace!r}: "
                f"{len(bad)}/{len(entries)} entries failed "
                f"(first: {bad[:3]})")

    def _absorb_self_writes(self, ns, entries) -> None:
        """The evaluator's own raw-namespace output writes bump source
        shard versions; re-snapshot exactly those shards POST-write so
        the next pass does not self-invalidate every rule sharing a
        shard with an output series. (An external write racing into the
        same shard inside this tiny window is masked once; the next
        write to that shard re-invalidates.) A standing rule chained on
        another rule's raw output therefore does not re-fire from the
        output write alone — compose the upstream expr instead."""
        from m3_tpu.utils.ident import tags_to_id

        ids = list({tags_to_id(name, tags) for name, tags, _t, _v in entries})
        for sid in {int(s) for s in ns.shard_set.lookup_many(ids)}:
            shard = ns.shards.get(sid)
            if shard is not None:
                self._last_shard_versions[sid] = shard.data_version

    def _record_error(self, name: str, err: str) -> None:
        st = self._states.get(name)
        if st is not None:
            st.last_error = err

    # -- introspection ------------------------------------------------------

    def status(self) -> dict:
        """Per-rule evaluation state for /debug surfaces and the rig."""
        return {
            "source": self.source,
            "totals": dict(self.counts),
            "rules": {
                name: {"last_end_ns": st.last_end, "evals": st.evals,
                       "skips": st.skips, "shards": sorted(st.shards),
                       "error": st.last_error}
                for name, st in self._states.items()
            },
        }
