"""Cluster admin HTTP surface: database/namespace/placement/topic CRUD.

Role parity with the reference coordinator admin routes
(/root/reference/src/query/api/v1/httpd/handler.go:175-247 — database
create, namespace CRUD, placement init/add/remove/replace via
cluster/placementhandler, topic CRUD) so a cluster is stood up with curl
exactly like the reference quickstart. Namespaces live in a KV registry
that storage nodes watch (the dynamic namespace-registry role,
dbnode/namespace/dynamic); placements/topics use the KV helpers in
cluster/placement.py and msg/topic.py.
"""

from __future__ import annotations

import json

from m3_tpu.cluster import placement as pl
from m3_tpu.cluster.placement import Instance
from m3_tpu.msg import topic as topiclib

NAMESPACE_KEY = "namespaces/m3db"


class NotFoundError(KeyError):
    """Deliberate resource-not-found (maps to HTTP 404; a missing request
    field is a plain KeyError and maps to 400)."""


def load_namespace_registry(kv) -> dict[str, dict]:
    from m3_tpu.cluster.kv import KeyNotFound

    try:
        vv = kv.get(NAMESPACE_KEY)
    except KeyNotFound:
        return {}
    return json.loads(vv.data)


def store_namespace_registry(kv, registry: dict[str, dict]) -> int:
    return kv.set(NAMESPACE_KEY, json.dumps(registry).encode())


def update_namespace_registry(kv, fn, max_retries: int = 10) -> dict:
    """CAS read-modify-write of the registry: concurrent admin calls must
    not lose each other's namespaces."""
    from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch

    for _ in range(max_retries):
        try:
            vv = kv.get(NAMESPACE_KEY)
            registry, version = json.loads(vv.data), vv.version
        except KeyNotFound:
            registry, version = {}, 0
        registry = fn(dict(registry))
        try:
            kv.check_and_set(NAMESPACE_KEY, version,
                             json.dumps(registry).encode())
            return registry
        except VersionMismatch:
            continue
    raise RuntimeError("namespace registry CAS contention")


class AdminAPI:
    """Admin handlers; mounted under the coordinator HTTP server."""

    def __init__(self, db, kv=None, placement_key: str | None = None):
        self.db = db
        self.kv = kv
        self.placement_key = placement_key or pl.PLACEMENT_KEY

    def handle(self, method: str, path: str, q: dict, body: bytes):
        """Returns (status, payload) or None when the path isn't admin."""
        try:
            return self._route(method, path, q, body)
        except NotFoundError as e:
            return 404, json.dumps({"error": str(e).strip("'")}).encode()
        except Exception as e:  # noqa: BLE001 - incl. KeyError on a missing
            # request field, which is a BAD REQUEST, not a 404
            return 400, json.dumps({"error": str(e)}).encode()

    def _route(self, method, path, q, body):
        doc = json.loads(body) if body else {}
        if path == "/api/v1/database/create" and method == "POST":
            return self._database_create(doc)
        if path == "/api/v1/services/m3db/namespace":
            if method == "GET":
                return self._namespace_list()
            if method == "POST":
                return self._namespace_create(doc)
        if path.startswith("/api/v1/services/m3db/namespace/") and method == "DELETE":
            return self._namespace_delete(path.rsplit("/", 1)[1])
        if path == "/api/v1/services/m3db/placement":
            if method == "GET":
                return self._placement_get()
            if method == "POST":
                return self._placement_add(doc)
        if path == "/api/v1/services/m3db/placement/init" and method == "POST":
            return self._placement_init(doc)
        if path == "/api/v1/services/m3db/placement/replace" and method == "POST":
            return self._placement_replace(doc)
        if path.startswith("/api/v1/services/m3db/placement/") and method == "DELETE":
            return self._placement_remove(path.rsplit("/", 1)[1])
        if path == "/api/v1/topic":
            if method == "GET":
                return self._topic_get(q)
            if method == "POST":
                return self._topic_init(doc)
            if method == "DELETE":
                return self._topic_delete(q)
        if path == "/api/v1/topic/consumer" and method == "POST":
            return self._topic_add_consumer(doc)
        if path.startswith("/api/v1/topic/consumer/") and method == "DELETE":
            return self._topic_remove_consumer(q, path.rsplit("/", 1)[1])
        if path == "/api/v1/runtime":
            if method == "GET":
                return self._runtime_get()
            if method in ("POST", "PUT"):
                return self._runtime_set(doc)
        if path == "/api/v1/rules":
            if method == "GET":
                return self._rules_get()
            if method in ("POST", "PUT"):
                return self._rules_replace(doc, q)
        if path in ("/api/v1/rules/mapping", "/api/v1/rules/rollup") \
                and method == "POST":
            return self._rule_upsert(path.rsplit("/", 1)[1], doc)
        if (path.startswith("/api/v1/rules/mapping/")
                or path.startswith("/api/v1/rules/rollup/")) \
                and method == "DELETE":
            _, kind, name = path.rsplit("/", 2)
            return self._rule_delete(kind, name)
        return None

    # -- rules (R2 service role: CRUD over the KV rule store) --

    def _require_kv(self):
        if self.kv is None:
            raise ValueError("rules need a cluster KV")

    def _rules_get(self):
        from m3_tpu.metrics import rules_store as rstore

        self._require_kv()
        rs, version = rstore.load_ruleset(self.kv)
        doc = rstore.ruleset_to_doc(rs)
        doc["version"] = version
        return 200, json.dumps(doc).encode()

    def _rules_replace(self, doc: dict, q: dict):
        """Replace the whole ruleset; pass ?version= for optimistic
        concurrency against a previous GET."""
        from m3_tpu.metrics import rules_store as rstore

        self._require_kv()
        doc = {"mapping": doc.get("mapping", []),
               "rollup": doc.get("rollup", [])}
        expect = q.get("version")
        version = rstore.store_ruleset_doc(
            self.kv, doc, int(expect[0]) if expect else None)
        return 200, json.dumps({"version": version}).encode()

    def _rule_upsert(self, kind: str, doc: dict):
        """Add or replace ONE rule by name (CAS'd read-modify-write)."""
        from m3_tpu.metrics import rules_store as rstore

        self._require_kv()
        if not doc.get("name"):
            raise ValueError("rule needs a name")

        def mutate(full: dict) -> dict:
            rules = [r for r in full.get(kind, []) if r.get("name") != doc["name"]]
            rules.append(doc)
            full[kind] = rules
            return full

        _, version = rstore.update_ruleset_doc(self.kv, mutate)
        return 200, json.dumps({"version": version}).encode()

    def _rule_delete(self, kind: str, name: str):
        from m3_tpu.metrics import rules_store as rstore

        self._require_kv()

        def mutate(full: dict) -> dict:
            before = full.get(kind, [])
            after = [r for r in before if r.get("name") != name]
            if len(after) == len(before):
                # abort BEFORE any write: a 404'd delete must not bump the
                # version (spurious reloads, broken optimistic PUTs) or
                # create the key on an empty store
                raise NotFoundError(name)
            full[kind] = after
            return full

        _, version = rstore.update_ruleset_doc(self.kv, mutate)
        return 200, json.dumps({"version": version}).encode()

    # -- runtime options (kvconfig role) --

    def _runtime_get(self):
        from m3_tpu.cluster.kv import KeyNotFound
        from m3_tpu.cluster.runtime import RUNTIME_KEY, RuntimeOptions

        if self.kv is None:
            raise ValueError("runtime options need a cluster KV")
        try:
            raw = self.kv.get(RUNTIME_KEY).data
            opts = RuntimeOptions.from_json(raw)
        except KeyNotFound:
            opts = RuntimeOptions()
        from dataclasses import asdict

        return 200, json.dumps(asdict(opts)).encode()

    def _runtime_set(self, doc: dict):
        """Validates the payload by round-tripping it through
        RuntimeOptions, then writes the kvconfig key; every watching
        service applies it live."""
        from m3_tpu.cluster.runtime import RUNTIME_KEY, RuntimeOptions

        from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch

        if self.kv is None:
            raise ValueError("runtime options need a cluster KV")
        unknown = set(doc) - set(RuntimeOptions.__dataclass_fields__)
        if unknown:
            raise ValueError(f"unknown runtime fields: {sorted(unknown)}")
        # partial update merged over the STORED options under CAS: two
        # operators updating different fields concurrently must both land
        for _ in range(16):
            try:
                vv = self.kv.get(RUNTIME_KEY)
                current, cur_version = json.loads(vv.data), vv.version
            except KeyNotFound:
                current, cur_version = {}, None
            current.update(doc)
            opts = RuntimeOptions.from_json(json.dumps(current).encode())
            try:
                if cur_version is None:
                    version = self.kv.set_if_not_exists(
                        RUNTIME_KEY, opts.to_json())
                else:
                    version = self.kv.check_and_set(
                        RUNTIME_KEY, cur_version, opts.to_json())
                break
            except VersionMismatch:
                continue
        else:
            raise ValueError("runtime update contention; retry")
        from dataclasses import asdict

        return 200, json.dumps(
            {"version": version, **asdict(opts)}
        ).encode()

    # -- database / namespaces --

    def _ns_options_doc(self, doc: dict) -> dict:
        out = {
            "retention": {
                "period": doc.get("retentionTime", doc.get("retention", "48h")),
                "block_size": doc.get("blockSize", "2h"),
            },
            "int_optimized": bool(doc.get("intOptimized", False)),
        }
        if doc.get("resolution"):
            # downsampled tier: its resolution drives retention-tier read
            # resolution (aggregated namespace attributes)
            out["resolution"] = doc["resolution"]
        return out

    def _create_local_namespace(self, name: str, opts_doc: dict) -> None:
        create = getattr(self.db, "create_namespace", None)
        if create is None:
            return
        from m3_tpu.services.coordinator import namespace_options

        create(name, namespace_options(opts_doc))

    def _validate_ns_options(self, opts_doc: dict) -> None:
        """Reject unparseable options BEFORE they land in the registry —
        a bad duration there would crash-loop every storage node's sync."""
        from m3_tpu.services.coordinator import namespace_options

        namespace_options(opts_doc)

    def _register_namespace(self, name: str, opts_doc: dict) -> None:
        self._validate_ns_options(opts_doc)
        if self.kv is not None:
            def add(reg):
                reg[name] = opts_doc
                return reg

            update_namespace_registry(self.kv, add)
        self._create_local_namespace(name, opts_doc)

    def _database_create(self, doc: dict):
        """The one-shot quickstart: namespace (+ placement for type=cluster)."""
        name = doc.get("namespaceName", "default")
        self._register_namespace(name, self._ns_options_doc(doc))
        out = {"namespace": name}
        if doc.get("type") == "cluster" and self.kv is not None and doc.get("instances"):
            _, pdoc = self._placement_init(doc)
            out["placement"] = json.loads(pdoc)
        return 200, json.dumps(out).encode()

    def _namespace_list(self):
        if self.kv is not None:
            registry = load_namespace_registry(self.kv)
        else:
            registry = {name: {} for name in getattr(self.db, "namespaces", {})}
        return 200, json.dumps({"registry": registry}).encode()

    def _namespace_create(self, doc: dict):
        name = doc["name"]
        self._register_namespace(name, doc.get("options")
                                 or self._ns_options_doc(doc))
        return 200, json.dumps({"created": name}).encode()

    def _namespace_delete(self, name: str):
        if self.kv is not None:
            def drop(reg):
                if name not in reg:
                    # abort INSIDE the CAS fn: no spurious registry write,
                    # and a retry that finds the name deletes it normally
                    raise NotFoundError(f"namespace {name!r} not registered")
                del reg[name]
                return reg

            update_namespace_registry(self.kv, drop)
        drop_local = getattr(self.db, "drop_namespace", None)
        if drop_local is not None:
            drop_local(name)
        else:
            namespaces = getattr(self.db, "namespaces", None)
            if namespaces is not None:
                namespaces.pop(name, None)
        return 200, json.dumps({"deleted": name}).encode()

    # -- placements --

    def _require_kv(self):
        if self.kv is None:
            raise ValueError("placement/topic admin requires a KV store "
                             "(cluster mode)")

    def _placement_doc(self, p) -> bytes:
        return json.dumps(json.loads(p.to_json())).encode()

    def _placement_get(self):
        self._require_kv()
        loaded = pl.load_placement(self.kv, self.placement_key)
        if loaded is None:
            raise NotFoundError("no placement")
        return 200, self._placement_doc(loaded[0])

    @staticmethod
    def _instance(doc: dict) -> Instance:
        return Instance(
            id=doc["id"],
            isolation_group=doc.get("isolation_group",
                                    doc.get("isolationGroup", "default")),
            weight=int(doc.get("weight", 1)),
            endpoint=doc.get("endpoint", ""),
        )

    def _placement_init(self, doc: dict):
        self._require_kv()
        instances = [self._instance(d) for d in doc["instances"]]
        p = pl.initial_placement(
            instances,
            n_shards=int(doc.get("num_shards", doc.get("numShards", 8))),
            replica_factor=int(doc.get("replication_factor",
                                       doc.get("replicationFactor", 1))),
        )
        pl.store_placement(self.kv, p, self.placement_key)
        return 200, self._placement_doc(p)

    def _placement_add(self, doc: dict):
        self._require_kv()
        inst = self._instance(doc.get("instance", doc))
        new = pl.cas_update_placement(
            self.kv, lambda p: pl.add_instance(p, inst), self.placement_key)
        return 200, self._placement_doc(new)

    def _placement_remove(self, instance_id: str):
        self._require_kv()
        new = pl.cas_update_placement(
            self.kv, lambda p: pl.remove_instance(p, instance_id),
            self.placement_key)
        return 200, self._placement_doc(new)

    def _placement_replace(self, doc: dict):
        self._require_kv()
        old_id = doc["leavingInstanceID"] if "leavingInstanceID" in doc else doc["old_id"]
        inst = self._instance(doc.get("candidate", doc.get("instance", doc)))
        new = pl.cas_update_placement(
            self.kv, lambda p: pl.replace_instance(p, old_id, inst),
            self.placement_key)
        return 200, self._placement_doc(new)

    # -- topics --

    def _topic_name(self, q: dict, doc: dict | None = None) -> str:
        if doc and doc.get("name"):
            return doc["name"]
        return q.get("topic", ["aggregated_metrics"])[0]

    def _topic_get(self, q):
        self._require_kv()
        t = topiclib.get_topic(self.kv, self._topic_name(q))
        if t is None:
            raise NotFoundError("no such topic")
        return 200, t.to_json()

    def _topic_init(self, doc: dict):
        self._require_kv()
        name = doc.get("name", "aggregated_metrics")
        if topiclib.get_topic(self.kv, name) is not None:
            # re-init would wipe registered consumer services
            return 409, json.dumps(
                {"error": f"topic {name!r} already exists"}).encode()
        t = topiclib.Topic(
            name=name,
            n_shards=int(doc.get("numberOfShards", doc.get("n_shards", 64))),
        )
        topiclib.create_topic(self.kv, t)
        return 200, t.to_json()

    def _topic_delete(self, q):
        self._require_kv()
        name = self._topic_name(q)
        topiclib.delete_topic(self.kv, name)
        return 200, json.dumps({"deleted": name}).encode()

    def _topic_add_consumer(self, doc: dict):
        self._require_kv()
        c = doc.get("consumerService", doc)
        try:
            t = topiclib.add_consumer(
            self.kv, self._topic_name({}, doc),
            topiclib.ConsumerService(
                c.get("serviceID", {}).get("name")
                if isinstance(c.get("serviceID"), dict)
                else c.get("service_id", c.get("serviceID", "")),
                c.get("consumptionType",
                      c.get("consumption_type", topiclib.SHARED)).lower(),
                ),
            )
        except KeyError as e:
            raise NotFoundError(str(e)) from None
        return 200, t.to_json()

    def _topic_remove_consumer(self, q, service_id: str):
        self._require_kv()
        try:
            t = topiclib.remove_consumer(self.kv, self._topic_name(q),
                                         service_id)
        except KeyError as e:
            raise NotFoundError(str(e)) from None
        return 200, t.to_json()
