"""M3QL front-end: the pipe-based query language, compiled to the SAME
AST the PromQL engine evaluates.

Role parity with the reference M3QL parser
(/root/reference/src/query/parser/m3ql/grammar.peg — macros, pipelines of
function calls with boolean/numeric/pattern/string/keyword arguments, and
parenthesized nesting). Where the reference lowers to its common DAG ops,
this compiles to m3_tpu.query.promql Expr nodes, so one evaluation engine
(and one set of device kernels) serves both languages.

Surface (the practically used M3QL core):

    fetch name:cpu.util host:web* dc:ny        # tag matchers; * ? globs
      | sum host dc                            # aggregate BY tags
      | avg | min | max | count | stddev       # no tags = collapse all
      | sumSeries / avgSeries ...              # explicit collapse aliases
      | perSecond [5m]                         # rate() over the window
      | increase [5m], irate, delta
      | movingAverage 5m                       # avg_over_time window
      | abs | ceil | floor | sqrt | log | exp  # elementwise math
      | scale 2.5 | offset -3                  # arithmetic with a constant
      | clamp-ish: removeAbove 10, removeBelow 1
      | > 5, >= 5, < 5, <= 5, == 5, != 5       # comparison filters
      | keepLastValue                          # last_over_time lookback
      | head 5 / topk-style limiting (top k) / bottom k
      | timeshift 1h                           # offset modifier
    macros:  m = fetch name:reqs | sum dc; m | perSecond

Keyword arguments (`sf:0.3`) are accepted wherever positional numbers are.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, replace

from m3_tpu.index.query import Matcher, MatchType
from m3_tpu.query.promql import (
    AggregateExpr,
    BinaryExpr,
    Call,
    Expr,
    MatrixSelector,
    NumberLiteral,
    VectorSelector,
)

NS = 1_000_000_000


class M3QLError(ValueError):
    pass


# -- tokenizer ---------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r\n]+)
  | (?P<comment>\#[^\n]*)
  | (?P<pipe>\|)
  | (?P<semi>;)
  | (?P<eq>=)
  | (?P<lparen>\()
  | (?P<rparen>\))
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<op><=|>=|==|!=|<|>)
  | (?P<word>[^ \t\r\n|;()="]+)
""", re.X)


@dataclass
class _Tok:
    kind: str
    text: str


def _tokenize(src: str) -> list[_Tok]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise M3QLError(f"bad character at {pos}: {src[pos]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        out.append(_Tok(kind, m.group()))
    out.append(_Tok("eof", ""))
    return out


# -- parser ------------------------------------------------------------------

_NUMBER_RE = re.compile(r"^-?\d+(\.\d+)?$")
_DURATION_RE = re.compile(r"^(\d+)(ms|s|m|h|d|w)$")
_DUR_NS = {"ms": 10**6, "s": NS, "m": 60 * NS, "h": 3600 * NS,
           "d": 86400 * NS, "w": 7 * 86400 * NS}


def _duration_ns(text: str) -> int | None:
    m = _DURATION_RE.match(text)
    if not m:
        return None
    return int(m.group(1)) * _DUR_NS[m.group(2)]


@dataclass
class _CallSpec:
    name: str
    args: list  # str | float | Expr (nested pipeline)
    keywords: dict


class _Parser:
    def __init__(self, toks: list[_Tok]):
        self.toks = toks
        self.i = 0
        self.macros: dict[str, Expr] = {}

    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def parse(self) -> Expr:
        # (macro ;)* pipeline
        while (self.peek().kind == "word"
               and self.toks[self.i + 1].kind == "eq"):
            name = self.next().text
            self.next()  # =
            self.macros[name] = self.pipeline()
            if self.next().kind != "semi":
                raise M3QLError(f"macro {name!r} must end with ';'")
        expr = self.pipeline()
        if self.peek().kind != "eof":
            raise M3QLError(f"trailing input at {self.peek().text!r}")
        return expr

    def pipeline(self) -> Expr:
        expr: Expr | None = None
        while True:
            spec = self.call_spec()
            expr = _compile(spec, expr, self.macros)
            if self.peek().kind == "pipe":
                self.next()
                continue
            return expr

    def call_spec(self) -> _CallSpec:
        t = self.peek()
        if t.kind == "lparen":
            self.next()
            inner = self.pipeline()
            if self.next().kind != "rparen":
                raise M3QLError("unbalanced parenthesis")
            return _CallSpec("__nested__", [inner], {})
        if t.kind not in ("word", "op"):
            raise M3QLError(f"expected function, got {t.text!r}")
        self.next()
        spec = _CallSpec(t.text, [], {})
        while True:
            a = self.peek()
            if a.kind == "lparen":
                self.next()
                inner = self.pipeline()
                if self.next().kind != "rparen":
                    raise M3QLError("unbalanced parenthesis")
                spec.args.append(inner)
                continue
            if a.kind == "string":
                self.next()
                spec.args.append(a.text[1:-1])
                continue
            if a.kind == "word":
                # keyword argument?  word ':' value is inside one token
                self.next()
                spec.args.append(a.text)
                continue
            return spec


def _glob_to_matcher(name: str, pattern: str) -> Matcher:
    if re.search(r"[*?{}\[\]]", pattern):
        rx = _glob_to_regex(pattern)
        return Matcher(MatchType.REGEXP, name.encode(), rx.encode())
    return Matcher(MatchType.EQUAL, name.encode(), pattern.encode())


def _glob_to_regex(glob: str) -> str:
    out = []
    i = 0
    while i < len(glob):
        ch = glob[i]
        if ch == "*":
            out.append(".*")
        elif ch == "?":
            out.append(".")
        elif ch == "{":
            j = glob.find("}", i)
            if j < 0:
                raise M3QLError(f"unclosed brace in {glob!r}")
            out.append("(" + "|".join(re.escape(p)
                                      for p in glob[i + 1:j].split(",")) + ")")
            i = j
        elif ch == "[":
            j = glob.find("]", i)
            if j < 0:
                raise M3QLError(f"unclosed bracket in {glob!r}")
            out.append(glob[i:j + 1])
            i = j
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


_AGG_OPS = {
    "sum": "sum", "avg": "avg", "min": "min", "max": "max",
    "count": "count", "stddev": "stddev", "stdev": "stddev",
    "median": "quantile",
}
_COLLAPSE = {"sumseries": "sum", "avgseries": "avg", "minseries": "min",
             "maxseries": "max", "countseries": "count"}
_RANGE_FNS = {"persecond": "rate", "increase": "increase", "irate": "irate",
              "delta": "delta", "rate": "rate"}
_MATH_FNS = {"abs", "ceil", "floor", "sqrt", "log", "exp", "ln", "log2",
             "log10"}
_DEFAULT_RANGE_NS = 5 * 60 * NS


def _num(spec: _CallSpec, idx: int, default=None) -> float:
    if idx < len(spec.args) and isinstance(spec.args[idx], str) \
            and _NUMBER_RE.match(spec.args[idx]):
        return float(spec.args[idx])
    if default is None:
        raise M3QLError(f"{spec.name} expects a numeric argument")
    return default


def _range_of(spec: _CallSpec, idx: int = 0) -> int:
    for a in spec.args[idx:]:
        if isinstance(a, str):
            d = _duration_ns(a)
            if d is not None:
                return d
    return _DEFAULT_RANGE_NS


def _compile(spec: _CallSpec, upstream: Expr | None, macros: dict) -> Expr:
    fn = spec.name.lower()
    if spec.name == "__nested__":
        return spec.args[0]
    if spec.name in macros:
        if upstream is not None:
            raise M3QLError(f"macro {spec.name!r} cannot take pipe input")
        return macros[spec.name]

    if fn == "fetch":
        if upstream is not None:
            raise M3QLError("fetch must start a pipeline")
        matchers = []
        for a in spec.args:
            if not isinstance(a, str) or ":" not in a:
                raise M3QLError(f"fetch expects tag:pattern, got {a!r}")
            tag, _, pattern = a.partition(":")
            tag = {"name": "__name__"}.get(tag, tag)
            matchers.append(_glob_to_matcher(tag, pattern))
        if not matchers:
            raise M3QLError("fetch needs at least one tag:pattern")
        return VectorSelector(None, matchers)

    if upstream is None:
        raise M3QLError(f"{spec.name!r} needs pipe input (start with fetch)")

    if fn in _AGG_OPS and fn != "median":
        tags = tuple(a for a in spec.args if isinstance(a, str))
        return AggregateExpr(_AGG_OPS[fn], upstream, grouping=tags,
                             without=False)
    if fn == "median":
        tags = tuple(a for a in spec.args if isinstance(a, str))
        return AggregateExpr("quantile", upstream,
                             param=NumberLiteral(0.5), grouping=tags)
    if fn in _COLLAPSE:
        return AggregateExpr(_COLLAPSE[fn], upstream)
    if fn in _RANGE_FNS:
        rng = _range_of(spec)
        return Call(_RANGE_FNS[fn],
                    [MatrixSelector(_require_selector(upstream, spec), rng)])
    if fn == "movingaverage":
        rng = _range_of(spec)
        return Call("avg_over_time",
                    [MatrixSelector(_require_selector(upstream, spec), rng)])
    if fn == "keeplastvalue":
        rng = _range_of(spec)
        return Call("last_over_time",
                    [MatrixSelector(_require_selector(upstream, spec), rng)])
    if fn in _MATH_FNS:
        name = {"log": "ln"}.get(fn, fn)
        return Call(name, [upstream])
    if fn == "scale":
        return BinaryExpr("*", upstream, NumberLiteral(_num(spec, 0)))
    if fn == "offset":
        return BinaryExpr("+", upstream, NumberLiteral(_num(spec, 0)))
    if fn == "removeabove":
        return Call("clamp_max", [upstream, NumberLiteral(_num(spec, 0))])
    if fn == "removebelow":
        return Call("clamp_min", [upstream, NumberLiteral(_num(spec, 0))])
    if fn == "timeshift":
        sel = _require_selector(upstream, spec)
        d = _duration_ns(spec.args[0]) if spec.args else None
        if d is None:
            raise M3QLError("timeshift expects a duration")
        # a fresh selector, never an in-place mutation: macro bodies are
        # expanded BY REFERENCE, so writing offset_ns on the shared
        # upstream would timeshift every other use of the macro too
        return replace(sel, offset_ns=d)
    if fn in ("top", "head", "highestmax", "highestcurrent"):
        k = _num(spec, 0, 5.0)
        return AggregateExpr("topk", upstream, param=NumberLiteral(k))
    if fn in ("bottom", "lowestcurrent"):
        k = _num(spec, 0, 5.0)
        return AggregateExpr("bottomk", upstream, param=NumberLiteral(k))
    if spec.name in ("<", "<=", ">", ">=", "==", "!="):
        return BinaryExpr(spec.name, upstream, NumberLiteral(_num(spec, 0)))
    raise M3QLError(f"unknown m3ql function {spec.name!r}")


def _require_selector(e: Expr, spec: _CallSpec) -> VectorSelector:
    if not isinstance(e, VectorSelector):
        raise M3QLError(
            f"{spec.name} needs raw fetched series (apply it before "
            "aggregations)")
    return e


def parse(src: str) -> Expr:
    """M3QL source -> promql Expr AST."""
    return _Parser(_tokenize(src)).parse()
