"""Fanout storage: merge the local zone with remote-zone coordinators.

The reference coordinator composes its local m3 storage with remote gRPC
storages behind one Storage interface and merges series results
(/root/reference/src/query/storage/fanout/storage.go; remote client
query/remote/client.go). This facade does the same for this framework's
storage contract — `namespaces[ns].query_ids / read / read_many` plus the
label APIs — so the PromQL/Graphite engines and the HTTP API run unchanged
over a multi-zone deployment.

Semantics:
- reads UNION series across zones; duplicate series ids merge their
  samples timestamp-deduped (local zone wins ties — it is authoritative
  for its own writes, matching the reference's local-preferred merge).
- writes stay zone-local: cross-zone replication is a deployment concern
  (the reference fanout likewise only fans out reads).
- a remote zone failing closed is either skipped (default, recorded via a
  warning counter — the reference's warn-on-partial-results mode) or
  fatal (strict=True, its fail mode).
"""

from __future__ import annotations

import logging

import numpy as np

from m3_tpu.storage.buffer import merge_dedup
from m3_tpu.utils import faults
from m3_tpu.utils.instrument import default_registry
from m3_tpu.utils.warnings import ReadWarning

log = logging.getLogger(__name__)
_scope = default_registry().root_scope("fanout")


class FanoutError(RuntimeError):
    """A remote zone failed and the fanout is configured strict."""


class FanoutNamespace:
    """One namespace viewed across the local db + remote zones."""

    # resolver.fetch_tagged threads its per-query warnings list through
    # the warnings= out-param (thread-safe) instead of draining the
    # shared last_warnings field
    supports_read_warnings = True
    # CLASS attribute, deliberately False: __getattr__ below delegates
    # unknown names to the LOCAL namespace, so without this shadow the
    # ragged fast path / hot-tier version probes would resolve to the
    # local namespace's methods and silently skip the remote zones
    supports_ragged_read = False
    has_version_truth = False

    def __init__(self, fdb: "FanoutDatabase", name: str):
        self._fdb = fdb
        self.name = name
        # partial-result contract (non-strict mode): zones skipped by the
        # last read/query call, as structured ReadWarnings — callers that
        # must distinguish "complete" from "served degraded" read this
        # instead of scraping logs/counters
        self.last_warnings: list[ReadWarning] = []

    @property
    def _local(self):
        """The local namespace, or None when this namespace exists only in
        a remote zone — callers skip the local leg then (the remote-only
        union semantics _Namespaces.__missing__ promises)."""
        try:
            return self._fdb.local.namespaces[self.name]
        except KeyError:
            return None

    # -- index scatter --

    def _zone_call(self, zone, fn, *args, warnings: list | None = None):
        import time as _time

        from m3_tpu.utils import querystats

        t0 = _time.perf_counter()
        try:
            faults.check("fanout.zone", zone=zone.name)
            return fn(*args)
        except Exception as e:  # noqa: BLE001 - per-zone failure policy
            if self._fdb.strict:
                raise FanoutError(f"remote zone {zone.name}: {e}") from e
            _scope.subscope("zone", zone=zone.name).counter("errors")
            log.warning("fanout: skipping zone %s: %s", zone.name, e)
            if warnings is not None:
                warnings.append(ReadWarning("fanout", zone.name, str(e)))
            return None
        finally:
            # per-zone share of this read, onto the active query record
            # (EXPLAIN ANALYZE shows one plan leg per remote zone)
            querystats.record_node_leg(f"zone:{zone.name}",
                                       _time.perf_counter() - t0)

    def query_ids(self, query, start_ns: int, end_ns: int, limit=None,
                  warnings: list | None = None):
        from m3_tpu.index.query import query_to_json

        warns: list[ReadWarning] = []
        local = self._local
        docs = list(local.query_ids(query, start_ns, end_ns, limit)) if local else []
        seen = {d.series_id for d in docs}
        qj = query_to_json(query)
        from m3_tpu.index.segment import Document

        for zone in self._fdb.zones:
            rows = self._zone_call(
                zone, zone.query_ids, self.name, qj, start_ns, end_ns, limit,
                warnings=warns)
            if not rows:
                continue
            for sid, fields in rows:
                if sid not in seen:
                    seen.add(sid)
                    docs.append(Document(0, sid, fields))
        docs.sort(key=lambda d: d.series_id)
        if limit is not None:
            docs = docs[:limit]
        self.last_warnings = warns
        if warnings is not None:
            warnings.extend(warns)
        return docs

    # -- reads (replica-style sample merge across zones) --

    def read_many(self, series_ids: list[bytes], start_ns: int, end_ns: int,
                  warnings: list | None = None):
        """One BATCHED read per zone: the local leg is the namespace's
        fused fetch+decode batch (one dispatch per (shard, block, volume)
        group) and each remote leg is one read_many RPC, so a fan-out over
        N series costs one batched request per node, not N.

        Partial-result contract (non-strict): a zone failing closed yields
        the surviving zones' merge plus one ReadWarning per skipped zone
        (self.last_warnings / the warnings out-param) — never an
        exception."""
        from m3_tpu.utils import trace

        with trace.span(trace.FANOUT_READ, namespace=self.name,
                        series=len(series_ids),
                        zones=len(self._fdb.zones)):
            return self._read_many_traced(series_ids, start_ns, end_ns,
                                          warnings)

    def _read_many_traced(self, series_ids, start_ns, end_ns, warnings):
        from m3_tpu.storage import pipeline

        warns: list[ReadWarning] = []
        local = self._local
        zones = self._fdb.zones
        # pipelined fan-out: every remote zone's read_many RPC goes in
        # flight BEFORE the local leg's fused fetch+decode runs on this
        # thread, so cross-zone network legs overlap the local decode
        # rung. Serial is pinned under the hatch or an armed fault plan
        # (the fanout.zone injection schedule must stay deterministic).
        futs = None
        if zones and series_ids and pipeline.active() \
                and not faults.enabled():
            futs = self._fly_zone_reads(zones, series_ids, start_ns, end_ns)
        if local is not None:
            merged = list(local.read_many(series_ids, start_ns, end_ns))
        else:
            empty_t = np.array([], dtype=np.int64)
            empty_v = np.array([], dtype=np.uint64)
            merged = [(empty_t, empty_v) for _ in series_ids]
        for k, zone in enumerate(zones):
            if futs is not None:
                remote = self._reap_zone_read(zone, futs[k], warns)
            else:
                remote = self._zone_call(
                    zone, zone.read_many, self.name, series_ids, start_ns,
                    end_ns, warnings=warns)
            if remote is None:
                continue
            for i, (rt, rv) in enumerate(remote):
                if len(rt) == 0:
                    continue
                lt, lv = merged[i]
                if len(lt) == 0:
                    merged[i] = (rt, rv)
                else:
                    # merge_dedup is last-write-wins on timestamp ties, so
                    # remote samples go FIRST and the local zone wins
                    merged[i] = merge_dedup(
                        np.concatenate([rt, lt]), np.concatenate([rv, lv]))
        self.last_warnings = warns
        if warnings is not None:
            warnings.extend(warns)
        return merged

    def _fly_zone_reads(self, zones, series_ids, start_ns, end_ns):
        """Submit every remote zone's read_many through the shared leg
        policy (pipeline.submit_client_leg: trace context re-activated
        per worker, timed, exceptions as values); `_reap_zone_read`
        applies the per-zone failure policy in zone order, so
        warnings/merge order match the serial loop."""
        from m3_tpu.storage import pipeline
        from m3_tpu.utils import trace

        tracer = trace.default_tracer()
        ctx = tracer.current()
        return [pipeline.submit_client_leg(
            lambda zone=zone: zone.read_many(self.name, series_ids,
                                             start_ns, end_ns),
            tracer, ctx, point_ctx="fanout_zone") for zone in zones]

    def _reap_zone_read(self, zone, fut, warns: list):
        """Consume one overlapped zone leg with _zone_call's exact
        policy: strict mode raises, otherwise the zone is skipped with a
        counter + ReadWarning; the leg rides EXPLAIN ANALYZE either way."""
        from m3_tpu.utils import querystats

        rows, err, dt = fut.result()
        querystats.record_node_leg(f"zone:{zone.name}", dt)
        if err is None:
            return rows
        if isinstance(err, faults.SimulatedCrash):
            raise err  # our own injected death, never a zone failure
        if self._fdb.strict:
            raise FanoutError(f"remote zone {zone.name}: {err}") from err
        _scope.subscope("zone", zone=zone.name).counter("errors")
        log.warning("fanout: skipping zone %s: %s", zone.name, err)
        warns.append(ReadWarning("fanout", zone.name, str(err)))
        return None

    def read(self, series_id: bytes, start_ns: int, end_ns: int):
        [(t, v)] = self.read_many([series_id], start_ns, end_ns)
        return t, v

    # -- label APIs --

    class _IndexFacade:
        def __init__(self, ns: "FanoutNamespace"):
            self._ns = ns

        def aggregate_field_names(self, start_ns, end_ns):
            ns = self._ns
            local = ns._local
            out = set(local.index.aggregate_field_names(start_ns, end_ns)) \
                if local else set()
            for zone in ns._fdb.zones:
                vals = ns._zone_call(
                    zone, zone.label_names, ns.name, start_ns, end_ns)
                if vals:
                    out.update(vals)
            return sorted(out)

        def aggregate_field_values(self, field, start_ns, end_ns):
            ns = self._ns
            local = ns._local
            out = set(local.index.aggregate_field_values(
                field, start_ns, end_ns)) if local else set()
            for zone in ns._fdb.zones:
                vals = ns._zone_call(
                    zone, zone.label_values, ns.name, field, start_ns, end_ns)
                if vals:
                    out.update(vals)
            return sorted(out)

    @property
    def index(self):
        return FanoutNamespace._IndexFacade(self)

    # passthrough attributes the engines occasionally consult (options,
    # limits); the LOCAL zone is authoritative for both
    def __getattr__(self, item):
        local = self._fdb.local.namespaces
        if self.name not in local:
            # a remote-only namespace has no local attributes to offer;
            # AttributeError (not KeyError) so getattr(ns, x, default) works
            raise AttributeError(
                f"namespace {self.name!r} has no local attribute {item!r}")
        return getattr(local[self.name], item)


class _Namespaces(dict):
    """Facade mapping that MIRRORS the local db's namespace listing
    (iteration/membership), while __getitem__ materializes a fanout view
    for any name — a namespace existing only in a remote zone is still
    queryable, matching the reference fanout's union semantics."""

    def __init__(self, fdb: "FanoutDatabase"):
        super().__init__()
        self._fdb = fdb

    def __missing__(self, name: str) -> FanoutNamespace:
        ns = FanoutNamespace(self._fdb, name)
        self[name] = ns
        return ns

    def _local_names(self):
        return list(self._fdb.local.namespaces)

    def __contains__(self, name) -> bool:  # type: ignore[override]
        return name in self._fdb.local.namespaces

    def __iter__(self):
        return iter(self._local_names())

    def __len__(self) -> int:
        return len(self._fdb.local.namespaces)

    def keys(self):
        return self._local_names()

    def items(self):
        return [(n, self[n]) for n in self._local_names()]

    def values(self):
        return [self[n] for n in self._local_names()]


class FanoutDatabase:
    """Database facade: local zone + remote read fanout. Write/lifecycle
    calls delegate to the local database untouched."""

    def __init__(self, local, zones, strict: bool = False):
        self.local = local
        self.zones = list(zones)
        self.strict = strict
        self.namespaces = _Namespaces(self)

    # local-zone passthroughs (writes, admin, lifecycle, limits)
    def __getattr__(self, item):
        return getattr(self.local, item)

    @property
    def limits(self):
        return getattr(self.local, "limits", None)

    @limits.setter
    def limits(self, v) -> None:
        self.local.limits = v

    def close(self) -> None:
        for z in self.zones:
            z.close()
        self.local.close()
