"""Windowed series math: the PromQL temporal-function core.

Role parity with the reference's temporal op library
(/root/reference/src/query/functions/temporal/{rate,aggregation,functions,
linear_regression}.go), reproducing upstream Prometheus numeric semantics
(extrapolated rates with counter-reset adjustment and zero-point capping,
population stddev, least-squares deriv) so results diff cleanly against
Prometheus — the comparator requirement in SURVEY.md §4.6.

Everything here is columnar: one call computes a whole [n_series, n_steps]
matrix from ragged per-series sample arrays using prefix sums + searchsorted
window bounds (no per-sample Python loops). Large fetches dispatch the
matrix math to the jax kernels in m3_tpu.ops.temporal (ops.dispatch policy,
M3_TPU_DEVICE_OPS to force); numpy remains the flag-off host fallback.
min/max over overlapping windows stay host-side (ufunc.reduceat has no
segment-op equivalent).
"""

from __future__ import annotations

import os

import numpy as np

from m3_tpu.utils import dispatch

NS = 1_000_000_000


def _use_device(raws: "RaggedSeries", eval_ts: np.ndarray) -> bool:
    from m3_tpu.ops import temporal

    work = len(raws.values) + raws.n_series * len(eval_ts)
    return dispatch.use_device(work, temporal.DEVICE_THRESHOLD)


class RaggedSeries:
    """Concatenated samples of S series + row offsets (CSR-style)."""

    def __init__(self, times: np.ndarray, values: np.ndarray, offsets: np.ndarray):
        self.times = times  # [N] int64 ns, ascending within each row
        self.values = values  # [N] float64
        self.offsets = offsets  # [S+1] int64 row boundaries

    @classmethod
    def from_lists(cls, per_series: list[tuple[np.ndarray, np.ndarray]]):
        if per_series:
            times = np.concatenate([t for t, _ in per_series])
            values = np.concatenate([v for _, v in per_series])
            lens = np.array([len(t) for t, _ in per_series], np.int64)
        else:
            times = np.empty(0, np.int64)
            values = np.empty(0, np.float64)
            lens = np.empty(0, np.int64)
        offsets = np.concatenate([[0], np.cumsum(lens)])
        return cls(times, values, offsets)

    @property
    def n_series(self) -> int:
        return len(self.offsets) - 1

    def window_bounds(self, eval_ts: np.ndarray, range_ns: int):
        """[lo, hi) sample index bounds of window (t-range, t] per
        (series, step)."""
        S = self.n_series
        lo = np.empty((S, len(eval_ts)), np.int64)
        hi = np.empty((S, len(eval_ts)), np.int64)
        for s in range(S):
            a, b = self.offsets[s], self.offsets[s + 1]
            row = self.times[a:b]
            lo[s] = a + np.searchsorted(row, eval_ts - range_ns, side="right")
            hi[s] = a + np.searchsorted(row, eval_ts, side="right")
        return lo, hi

    def window_bounds_batch(self, eval_ts: np.ndarray, range_ns: int):
        """window_bounds without the per-series Python loop, for an
        ASCENDING eval grid (what the engine always evaluates on).

        Inverts the search: instead of S x T binary searches over sample
        rows, every SAMPLE finds its first covering step in the tiny
        eval grid (one N x log T searchsorted, cache-hot), and the per-
        (series, step) counts come from a 2-D bincount + cumsum along
        steps — hi[s, t] = offsets[s] + #{samples in row s with time <=
        eval_ts[t]} by construction. The whole-query compiler's host
        prep uses this; a 100k-series fetch costs two vectorized passes,
        not 200k searchsorted calls. Falls back to the loop for
        non-ascending grids."""
        S = self.n_series
        T = len(eval_ts)
        n = len(self.times)
        if S == 0 or n == 0 or T == 0:
            z = np.zeros((S, T), np.int64)
            return z, z.copy()
        diffs = np.diff(eval_ts)
        if not bool((diffs >= 0).all()) \
                or S * (T + 1) > (1 << 26):  # bincount scratch cap ~0.5GB
            return self.window_bounds(eval_ts, range_ns)
        row_id = np.repeat(np.arange(S, dtype=np.int64),
                           np.diff(self.offsets))

        def counts(grid: np.ndarray) -> np.ndarray:
            # first step whose grid value >= sample time: the sample is
            # inside windows ending at that step and later (last slot =
            # outside every window, dropped before the cumsum)
            W = len(grid)
            pos = np.searchsorted(grid, self.times, side="left")
            hist = np.bincount(row_id * (W + 1) + pos,
                               minlength=S * (W + 1))
            return np.cumsum(hist.reshape(S, W + 1)[:, :W], axis=1)

        base = self.offsets[:-1][:, None]
        step = int(diffs[0]) if T > 1 else 0
        if step > 0 and range_ns % step == 0 \
                and bool((diffs == step).all()) \
                and S * (T + range_ns // step + 1) <= (1 << 26):
            # uniform grid, range a step multiple (every dashboard query):
            # lo's grid is hi's shifted k steps, so ONE counts pass over
            # the k-extended grid yields both bound matrices
            k = range_ns // step
            ext = np.concatenate([
                eval_ts[0] - np.arange(k, 0, -1, dtype=np.int64) * step,
                eval_ts])
            c = counts(ext)
            hi = base + c[:, k:]
            lo = base + c[:, :T]
        else:
            hi = base + counts(eval_ts)
            lo = base + counts(eval_ts - range_ns)
        return lo.astype(np.int64), hi.astype(np.int64)


def instant_values(raws: RaggedSeries, eval_ts: np.ndarray, lookback_ns: int):
    """Instant-vector matrix [S, n_steps]: latest sample in (t-lookback, t],
    NaN when none (the PromQL staleness rule)."""
    if len(raws.values) == 0:
        return np.full((raws.n_series, len(eval_ts)), np.nan)
    lo, hi = raws.window_bounds(eval_ts, lookback_ns)
    device = _use_device(raws, eval_ts)
    dispatch.record("temporal.instant_values", device)
    if device:
        from m3_tpu.ops import temporal

        return temporal.instant_values(raws.values, lo, hi)
    has = hi > lo
    idx = np.clip(hi - 1, 0, len(raws.values) - 1)
    return np.where(has, raws.values[idx], np.nan)


def _window_sums(raws: RaggedSeries, lo, hi, arr):
    """Sum of arr over [lo, hi) via prefix sums."""
    csum = np.concatenate([[0.0], np.cumsum(arr, dtype=np.float64)])
    return csum[hi] - csum[lo]


def _reduceat(op, arr, lo, hi, empty_fill):
    """Per-window reduce for overlapping [lo, hi) windows via ufunc.reduceat."""
    lo_f, hi_f = lo.ravel(), hi.ravel()
    n = len(arr)
    if n == 0:
        return np.full(lo.shape, empty_fill)
    pairs = np.empty(2 * len(lo_f), np.int64)
    pairs[0::2] = np.minimum(lo_f, n - 1)
    pairs[1::2] = np.minimum(hi_f, n - 1)
    # reduceat([i, j]) reduces arr[i:j] at even slots (arr[i] when i >= j)
    red = op.reduceat(arr, pairs)[0::2]
    red = np.where(hi_f > lo_f, red, empty_fill)
    # windows whose hi was clipped from n to n-1 are missing the last sample
    clipped = (hi_f == n) & (hi_f > lo_f)
    if clipped.any():
        red = np.where(clipped, op(red, arr[-1]), red)
    return red.reshape(lo.shape)


def over_time(fn: str, raws: RaggedSeries, eval_ts: np.ndarray, range_ns: int):
    """<fn>_over_time matrices; NaN where the window holds no samples."""
    lo, hi = raws.window_bounds(eval_ts, range_ns)
    count = (hi - lo).astype(np.float64)
    empty = count == 0
    if fn in ("sum", "avg", "stddev", "stdvar") and _use_device(raws, eval_ts):
        from m3_tpu.ops import temporal

        dispatch.record("temporal.over_time", True)
        dcount, s1, s2 = temporal.sum_avg_std(raws.values, lo, hi)
        if fn == "sum":
            return np.where(empty, np.nan, s1)
        if fn == "avg":
            return np.where(empty, np.nan, s1 / np.where(empty, 1, dcount))
        mean = s1 / np.where(empty, 1, dcount)
        var = np.maximum(s2 / np.where(empty, 1, dcount) - mean**2, 0.0)
        out = var if fn == "stdvar" else np.sqrt(var)
        return np.where(empty, np.nan, out)
    if fn in ("sum", "avg", "stddev", "stdvar"):
        dispatch.record("temporal.over_time", False)
    if fn == "count":
        return np.where(empty, np.nan, count)
    if fn == "present":
        return np.where(empty, np.nan, 1.0)
    if fn == "sum":
        return np.where(empty, np.nan, _window_sums(raws, lo, hi, raws.values))
    if fn == "avg":
        s = _window_sums(raws, lo, hi, raws.values)
        return np.where(empty, np.nan, s / np.where(empty, 1, count))
    if fn in ("stddev", "stdvar"):
        s1 = _window_sums(raws, lo, hi, raws.values)
        s2 = _window_sums(raws, lo, hi, raws.values**2)
        mean = s1 / np.where(empty, 1, count)
        var = np.maximum(s2 / np.where(empty, 1, count) - mean**2, 0.0)
        out = var if fn == "stdvar" else np.sqrt(var)
        return np.where(empty, np.nan, out)
    if fn in ("min", "max"):
        from m3_tpu.ops import temporal

        n = len(raws.values)
        max_len = int((hi - lo).max()) if lo.size else 0
        device = (_use_device(raws, eval_ts)
                  and temporal.minmax_levels(max_len)
                  * dispatch.next_pow2(n) <= temporal.MINMAX_SCRATCH_ELEMS)
        dispatch.record("temporal.window_minmax", device)
        if device:
            return temporal.window_minmax(raws.values, lo, hi, fn == "min")
        op = np.minimum if fn == "min" else np.maximum
        return _reduceat(op, raws.values, lo, hi, np.nan)
    if fn == "last":
        idx = np.clip(hi - 1, 0, max(len(raws.values) - 1, 0))
        return np.where(empty, np.nan, raws.values[idx] if len(raws.values) else np.nan)
    if fn == "changes":
        prev = np.concatenate([[np.nan], raws.values[:-1]])
        is_first = np.zeros(len(raws.values), bool)
        is_first[raws.offsets[:-1][raws.offsets[:-1] < len(is_first)]] = True
        changed = (raws.values != prev) & ~is_first
        # NaN -> NaN is not a change (Prometheus: both NaN means no change)
        both_nan = np.isnan(raws.values) & np.isnan(prev)
        changed &= ~both_nan
        c = _window_sums(raws, lo, hi, changed.astype(np.float64))
        # the first sample in a window has no predecessor inside it: subtract
        # a change counted at lo when its predecessor is outside the window
        first_in_window_changed = changed[np.clip(lo, 0, max(len(changed) - 1, 0))] if len(changed) else np.zeros(lo.shape)
        c -= np.where((hi > lo), first_in_window_changed.astype(np.float64), 0.0)
        return np.where(empty, np.nan, c)
    if fn == "resets":
        prev = np.concatenate([[np.inf], raws.values[:-1]])
        is_first = np.zeros(len(raws.values), bool)
        is_first[raws.offsets[:-1][raws.offsets[:-1] < len(is_first)]] = True
        reset = (raws.values < prev) & ~is_first
        c = _window_sums(raws, lo, hi, reset.astype(np.float64))
        first_in_window_reset = reset[np.clip(lo, 0, max(len(reset) - 1, 0))] if len(reset) else np.zeros(lo.shape)
        c -= np.where((hi > lo), first_in_window_reset.astype(np.float64), 0.0)
        return np.where(empty, np.nan, c)
    raise ValueError(f"unknown over_time fn {fn}")


def _reset_adjusted(raws: RaggedSeries) -> np.ndarray:
    """Counter values with resets accumulated (monotonized per series)."""
    v = raws.values
    prev = np.concatenate([[0.0], v[:-1]])
    is_first = np.zeros(len(v), bool)
    starts = raws.offsets[:-1]
    is_first[starts[starts < len(v)]] = True
    drop = np.where((v < prev) & ~is_first, prev, 0.0)
    # accumulate drops within each series: global cumsum minus row base
    cdrop = np.cumsum(drop)
    row_base = np.concatenate([[0.0], cdrop])[raws.offsets[:-1]]
    row_base_per_sample = np.repeat(row_base, np.diff(raws.offsets))
    return v + (cdrop - row_base_per_sample) + 0.0 if len(v) else v


def extrapolated_rate(
    raws: RaggedSeries,
    eval_ts: np.ndarray,
    range_ns: int,
    is_counter: bool,
    is_rate: bool,
):
    """rate/increase/delta with upstream Prometheus extrapolation.

    Mirrors promql extrapolatedRate: extrapolate to the window edges unless
    the first/last samples are further than 1.1x the average sample spacing
    from them, and (counters) cap start extrapolation at the zero point.
    """
    n = len(raws.values)
    if n == 0:
        return np.full((raws.n_series, len(eval_ts)), np.nan)

    device = _use_device(raws, eval_ts)
    dispatch.record("temporal.extrapolated_rate", device)
    if device:
        from m3_tpu.ops import temporal

        lo, hi = raws.window_bounds(eval_ts, range_ns)
        adj = (temporal.reset_adjusted(raws.values, raws.offsets)
               if is_counter else raws.values)
        return temporal.extrapolated_rate(
            raws.values, adj, raws.times, lo, hi, eval_ts, range_ns,
            is_counter, is_rate,
        )

    # CPU serving path: the native columnar kernel (same math, pointer-walk
    # windows — skips the per-series searchsorted loop entirely) when
    # available and the fetch is big enough to amortize FFI; requires the
    # ascending step grid the engine always evaluates on.
    work = n + raws.n_series * len(eval_ts)
    if (work >= 16_384 and os.environ.get("M3_TPU_NATIVE_OPS") != "0"
            and len(eval_ts) > 0 and bool((np.diff(eval_ts) >= 0).all())):
        from m3_tpu.ops import native_hostops

        if native_hostops.available():
            dispatch.counters["temporal.extrapolated_rate[native]"] += 1
            return native_hostops.rate_csr(raws.times, raws.values,
                                           raws.offsets, eval_ts, range_ns,
                                           is_counter, is_rate)

    lo, hi = raws.window_bounds(eval_ts, range_ns)
    count = (hi - lo).astype(np.float64)
    ok = count >= 2
    safe_lo = np.clip(lo, 0, max(n - 1, 0))
    safe_hi = np.clip(hi - 1, 0, max(n - 1, 0))
    v = _reset_adjusted(raws) if is_counter else raws.values
    first_v = v[safe_lo]
    last_v = v[safe_hi]
    raw_first_v = raws.values[safe_lo]
    first_t = raws.times[safe_lo].astype(np.float64)
    last_t = raws.times[safe_hi].astype(np.float64)
    result = last_v - first_v

    window_start = (eval_ts - range_ns).astype(np.float64)[None, :]
    window_end = eval_ts.astype(np.float64)[None, :]
    sampled = (last_t - first_t) / NS
    dur_to_start = (first_t - window_start) / NS
    dur_to_end = (window_end - last_t) / NS
    avg_between = sampled / np.maximum(count - 1, 1)
    threshold = avg_between * 1.1

    if is_counter:
        # don't extrapolate below zero (upstream caps BEFORE the threshold)
        with np.errstate(divide="ignore", invalid="ignore"):
            dur_to_zero = np.where(result > 0, sampled * (raw_first_v / result), np.inf)
        dur_to_start = np.where(
            (result > 0) & (raw_first_v >= 0) & (dur_to_zero < dur_to_start),
            dur_to_zero,
            dur_to_start,
        )

    dur_to_start = np.where(dur_to_start >= threshold, avg_between / 2, dur_to_start)
    dur_to_end = np.where(dur_to_end >= threshold, avg_between / 2, dur_to_end)

    extrap = sampled + dur_to_start + dur_to_end
    with np.errstate(divide="ignore", invalid="ignore"):
        factor = np.where(sampled > 0, extrap / sampled, np.nan)
        out = result * factor
        if is_rate:
            out = out / (range_ns / NS)
    return np.where(ok & (sampled > 0), out, np.nan)


def holt_winters(raws: RaggedSeries, eval_ts: np.ndarray, range_ns: int,
                 sf: float, tf: float):
    """Double exponential smoothing per window (upstream Prometheus
    holt_winters / the reference temporal/holt_winters.go:90-140):
    smoothed value s and trend b fold over the window's non-NaN samples;
    needs >= 2 samples, NaN otherwise.

    Columnar formulation: one pass over window OFFSETS with [S, n_steps]
    state matrices — the per-sample recurrence is inherently sequential,
    so the vectorization axis is (series x step), not time.
    """
    lo, hi = raws.window_bounds(eval_ts, range_ns)
    n = len(raws.values)
    if n == 0:
        return np.full(lo.shape, np.nan)
    device = _use_device(raws, eval_ts)
    dispatch.record("temporal.holt_winters", device)
    if device:
        from m3_tpu.ops import temporal

        return temporal.holt_winters(raws.values, lo, hi, sf, tf)
    max_len = int((hi - lo).max()) if lo.size else 0
    shape = lo.shape
    found_first = np.zeros(shape, bool)
    found_second = np.zeros(shape, bool)
    prev = np.zeros(shape)
    curr = np.zeros(shape)
    trend = np.zeros(shape)
    idx = np.zeros(shape, np.int64)  # non-NaN samples consumed so far
    for j in range(max_len):
        pos = lo + j
        valid = pos < hi
        val = raws.values[np.clip(pos, 0, n - 1)]
        valid &= ~np.isnan(val)
        take_first = valid & ~found_first
        curr = np.where(take_first, val, curr)
        idx = idx + take_first
        found_first |= take_first
        sub = valid & found_first & ~take_first
        take_second = sub & ~found_second
        trend = np.where(take_second, val - curr, trend)
        found_second |= take_second
        # calcTrendValue(i-1): the second sample (i-1 == 0) uses b as-is
        tv = np.where(idx == 1, trend,
                      tf * (curr - prev) + (1 - tf) * trend)
        new_curr = sf * val + (1 - sf) * (curr + tv)
        prev = np.where(sub, curr, prev)
        trend = np.where(sub, tv, trend)
        curr = np.where(sub, new_curr, curr)
        idx = idx + sub
    return np.where(found_second, curr, np.nan)


def instant_delta(raws: RaggedSeries, eval_ts: np.ndarray, range_ns: int,
                  is_counter: bool, is_rate: bool):
    """irate/idelta: from the last two samples in the window."""
    lo, hi = raws.window_bounds(eval_ts, range_ns)
    ok = (hi - lo) >= 2
    n = len(raws.values)
    if n == 0:
        return np.full(lo.shape, np.nan)
    i_last = np.clip(hi - 1, 0, n - 1)
    i_prev = np.clip(hi - 2, 0, n - 1)
    v_last, v_prev = raws.values[i_last], raws.values[i_prev]
    t_last = raws.times[i_last].astype(np.float64)
    t_prev = raws.times[i_prev].astype(np.float64)
    diff = v_last - v_prev
    if is_counter:
        diff = np.where(v_last < v_prev, v_last, diff)
    out = diff
    if is_rate:
        dt = (t_last - t_prev) / NS
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(dt > 0, diff / dt, np.nan)
    return np.where(ok, out, np.nan)


def linear_regression(raws: RaggedSeries, eval_ts: np.ndarray, range_ns: int,
                      predict_offset_s: float | None = None):
    """deriv (slope) / predict_linear via least squares over each window.

    Times are re-centered on the window's first sample (upstream's intercept
    time) before the sums, keeping t^2 within float64 precision.
    """
    lo, hi = raws.window_bounds(eval_ts, range_ns)
    count = (hi - lo).astype(np.float64)
    ok = count >= 2
    n = len(raws.values)
    if n == 0:
        return np.full(lo.shape, np.nan)
    t0 = raws.times[0] if n else 0
    x = (raws.times - t0).astype(np.float64) / NS  # seconds, small magnitude
    v = raws.values
    sx = _window_sums(raws, lo, hi, x)
    sv = _window_sums(raws, lo, hi, v)
    sxx = _window_sums(raws, lo, hi, x * x)
    sxv = _window_sums(raws, lo, hi, x * v)
    cnt = np.where(count > 0, count, 1)
    # re-center on the window's first sample time c:
    c = x[np.clip(lo, 0, n - 1)]
    #   sum((x-c)v) = sxv - c*sv ; sum(x-c) = sx - cnt*c
    #   sum((x-c)^2) = sxx - 2c*sx + cnt*c^2
    sxv_c = sxv - c * sv
    sx_c = sx - cnt * c
    sxx_c = sxx - 2 * c * sx + cnt * c * c
    with np.errstate(divide="ignore", invalid="ignore"):
        cov = sxv_c - sx_c * sv / cnt
        var = sxx_c - sx_c * sx_c / cnt
        slope = cov / var
        intercept = sv / cnt - slope * sx_c / cnt
    if predict_offset_s is None:
        return np.where(ok & (var > 0), slope, np.nan)
    # predict at eval time + offset, in the re-centered coordinate system
    eval_x = (eval_ts[None, :] - t0).astype(np.float64) / NS - c
    pred = intercept + slope * (eval_x + predict_offset_s)
    return np.where(ok & (var > 0), pred, np.nan)
