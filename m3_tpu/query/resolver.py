"""Retention-tier read resolution: route a query's time range to the raw
and/or downsampled namespaces and stitch the results.

Role parity with the reference's aggregated-namespace fanout
(/root/reference/src/query/storage/m3/cluster_resolver.go:34-120 — choose
unaggregated vs per-policy aggregated namespaces by retention coverage,
preferring completeness then resolution — and storage.go:183-757, which
merges the fan-out). Without this, downsampled data is write-only: a query
past raw retention would return nothing even though the 1m rollup holds it
(round-4 VERDICT missing #1).

Selection semantics (the reference's "default" fanout option):
- if the unaggregated namespace covers the query start, read it alone;
- otherwise read every namespace that intersects the range, finest
  resolution first, and stitch per series: each series takes the finer
  tier's samples from that tier's earliest sample onward and fills the
  older span from coarser tiers — so a rate() spanning the boundary sees
  one continuous, deduplicated stream.

Cheapest-tier resolution (resolve_read, ROADMAP #2): BEFORE the coverage
fallback above, a query whose step is coarse enough is routed to the
cheapest (coarsest-resolution) COMPLETE aggregated namespace that covers
its range — long-range dashboards read tiny pre-aggregated series
instead of decoding raw samples. `M3_TPU_TIER_RESOLVE=0` pins reads to
the retention-driven path (raw within retention) for parity testing.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Tier:
    name: str
    resolution_ns: int  # 0 = raw
    retention_ns: int
    complete: bool = False  # holds EVERY metric (downsample-all fed)


def namespace_tiers(db) -> list[Tier]:
    """Every namespace as a tier, from its options."""
    out = []
    for name in list(db.namespaces):
        ns = db.namespaces[name]
        opts = getattr(ns, "opts", None)
        if opts is None:
            continue
        out.append(Tier(name, opts.aggregated_resolution_ns,
                        opts.retention.retention_ns,
                        getattr(opts, "aggregated_complete", False)))
    return out


def tier_resolution_enabled() -> bool:
    """M3_TPU_TIER_RESOLVE=0 disables cheapest-tier selection (reads pin
    to the retention-driven raw path). Read per query so operators and
    parity tests can flip the hatch on a live process."""
    return os.environ.get("M3_TPU_TIER_RESOLVE") != "0"


def resolve_read(db, unagg: str, t_min: int, t_max: int, step_ns: int,
                 range_ns: int = 0, now_ns: int | None = None
                 ) -> tuple[list[str], dict]:
    """Namespaces to read for one selector fetch, plus the tier-choice
    record the explain surface reports.

    Choice matrix (cheapest covering tier wins):
    - candidates are COMPLETE aggregated tiers whose resolution covers
      the requested grid (resolution <= step) and window (2*resolution
      <= range for range selectors — a rate needs >= 2 samples per
      window) and whose retention covers the range start;
    - among candidates the COARSEST resolution wins (fewest samples
      decoded); resolution ties break to the longer retention, then the
      lexically smaller name (determinism);
    - no candidate (fine step, partial tiers, uncovered range) falls
      back to the retention-driven resolve_namespaces fanout: raw alone
      when it covers, else finest-first stitching.
    """
    now_ns = now_ns if now_ns is not None else time.time_ns()
    if not tier_resolution_enabled():
        return [unagg], {"mode": "pinned_raw", "namespaces": [unagg]}
    if step_ns > 0:
        best = None
        for t in namespace_tiers(db):
            if t.name == unagg or t.resolution_ns <= 0 or not t.complete:
                continue
            if t.resolution_ns > step_ns:
                continue
            if range_ns and 2 * t.resolution_ns > range_ns:
                continue
            if now_ns - t.retention_ns > t_min:
                continue
            pref = (t.resolution_ns, t.retention_ns)
            if (best is None
                    or pref > (best.resolution_ns, best.retention_ns)
                    or (pref == (best.resolution_ns, best.retention_ns)
                        and t.name < best.name)):
                best = t
        if best is not None:
            return [best.name], {
                "mode": "aggregated", "namespaces": [best.name],
                "resolution_ns": best.resolution_ns,
                "retention_ns": best.retention_ns,
                "step_ns": step_ns,
            }
    ns_list = resolve_namespaces(db, unagg, t_min, t_max, now_ns)
    mode = "raw" if ns_list == [unagg] else "stitched"
    return ns_list, {"mode": mode, "namespaces": list(ns_list),
                     "step_ns": step_ns}


def resolve_namespaces(db, unagg: str, t_min: int, t_max: int,
                       now_ns: int | None = None) -> list[str]:
    """Ordered namespaces to read for [t_min, t_max): finest first.

    Mirrors cluster_resolver.go's coverage rule: a tier covers the query
    when now - retention <= t_min. The unaggregated tier wins outright
    when it covers; otherwise all intersecting tiers fan out, ordered
    raw-then-increasing-resolution so the stitch prefers finer data.
    """
    now_ns = now_ns if now_ns is not None else time.time_ns()
    tiers = namespace_tiers(db)
    raw = next((t for t in tiers if t.name == unagg), None)
    if raw is None:
        # no tier metadata for the unaggregated namespace (e.g. a cluster
        # client DB exposing remote namespaces without local options):
        # tier resolution cannot apply — read it directly, old behavior
        return [unagg]
    if now_ns - raw.retention_ns <= t_min:
        return [unagg]
    # tiers that hold ANY of the range (now - retention < t_max)
    live = [t for t in tiers if now_ns - t.retention_ns < t_max]
    agg = sorted((t for t in live if t.name != unagg and t.resolution_ns > 0),
                 key=lambda t: t.resolution_ns)
    out = [t.name for t in ([raw] if raw in live else [])] + [t.name for t in agg]
    return out or [unagg]


def fetch_tagged_ragged(db, namespaces: list[str], index_query, t_min: int,
                        t_max: int, limit=None, keep_empty: bool = False,
                        warnings: list | None = None):
    """Single-tier fast path of fetch_tagged returning the RAGGED CSR
    (docs, times, value_bits, offsets) — or None when the shape needs
    the stitching path (multi-tier fanout, cluster facades without a
    ragged surface).  Row order matches fetch_tagged exactly: matched
    docs in index order with empty series dropped (or appended at the
    end under keep_empty) — dropping/reordering empty rows never moves
    sample data, so the CSR arrays come through untouched."""
    from m3_tpu.utils import querystats

    if len(namespaces) != 1:
        return None
    ns = db.namespaces[namespaces[0]]
    # capability marker, NOT hasattr: delegating facades (fanout) would
    # resolve a hasattr probe through __getattr__ to the local namespace
    # and this fast path would silently skip their remote legs
    if not getattr(ns, "supports_ragged_read", False):
        return None
    with querystats.stage("query_ids"):
        if limit is not None:
            docs = ns.query_ids(index_query, t_min, t_max, limit=limit)
        else:
            docs = ns.query_ids(index_query, t_min, t_max)
    querystats.record(series_matched=len(docs))
    ids = [d.series_id for d in docs]
    with querystats.stage("read_many"):
        if warnings is not None and getattr(ns, "supports_read_warnings",
                                            False):
            # cluster facade on the CSR path: its partial-read warnings
            # thread through the same per-call out-param fetch_tagged
            # uses (never read back from shared facade state)
            times, vbits, offsets = ns.read_many_ragged(
                ids, t_min, t_max, warnings=warnings)
        else:
            times, vbits, offsets = ns.read_many_ragged(ids, t_min, t_max)
    lens = np.diff(offsets)
    if not (lens == 0).any():
        return docs, times, vbits, offsets
    nz = np.nonzero(lens > 0)[0]
    order = np.concatenate([nz, np.nonzero(lens == 0)[0]]) \
        if keep_empty else nz
    docs = [docs[i] for i in order.tolist()]
    new_offsets = np.empty(len(order) + 1, np.int64)
    new_offsets[0] = 0
    np.cumsum(lens[order], out=new_offsets[1:])
    return docs, times, vbits, new_offsets


def fetch_tagged(db, namespaces: list[str], index_query, t_min: int,
                 t_max: int, limit=None, keep_empty: bool = False,
                 warnings: list | None = None):
    """Query + read the namespaces and stitch per series.

    Returns (docs, [(times, value_bits)]) aligned lists, one entry per
    distinct series id across all tiers. Stitch rule: walk tiers finest →
    coarsest; a coarser tier only contributes samples OLDER than the
    earliest sample already held for that series (no interleaving — the
    overlap region is served by the finer tier alone, the reference's
    completeness preference).

    Each tier's read is ONE batched read_many — storage fuses it into one
    fetch+decode dispatch per (shard, block, volume) group (or one RPC per
    node on cluster facades), so a 10k-series PromQL fetch costs a handful
    of decode dispatches, not 10k.

    ``warnings`` (out-param) accumulates the ReadWarnings degraded
    cluster facades recorded for these reads — the engine carries them to
    its results and the HTTP layer to response headers (PR-2 contract).
    It is threaded INTO facades advertising ``supports_read_warnings``
    (fanout, cluster session) as their own warnings= out-param, the
    per-call thread-safe channel — never read back from shared facade
    state, which concurrent queries would cross-contaminate.
    """
    from m3_tpu.utils import querystats

    by_id: dict[bytes, list] = {}  # id -> [doc, times, vbits]
    empties: dict[bytes, object] = {}  # matched but no samples anywhere
    for ns_name in namespaces:
        ns = db.namespaces[ns_name]
        kw = {"warnings": warnings} if warnings is not None and \
            getattr(ns, "supports_read_warnings", False) else {}
        with querystats.stage("query_ids"):
            if limit is not None:
                docs = ns.query_ids(index_query, t_min, t_max, limit=limit,
                                    **kw)
            else:
                docs = ns.query_ids(index_query, t_min, t_max, **kw)
        querystats.record(series_matched=len(docs))
        ids = [d.series_id for d in docs]
        with querystats.stage("read_many"):
            results = ns.read_many(ids, t_min, t_max, **kw)
        for doc, (times, vbits) in zip(docs, results):
            if len(times) == 0:
                if keep_empty and doc.series_id not in by_id:
                    empties.setdefault(doc.series_id, doc)
                continue
            cur = by_id.get(doc.series_id)
            if cur is None:
                by_id[doc.series_id] = [doc, times, vbits]
                continue
            cutoff = cur[1][0]  # earliest finer-tier sample
            older = times < cutoff
            if older.any():
                cur[1] = np.concatenate([times[older], cur[1]])
                cur[2] = np.concatenate([vbits[older], cur[2]])
    docs_out, series_out = [], []
    for doc, times, vbits in by_id.values():
        docs_out.append(doc)
        series_out.append((times, vbits))
    for sid, doc in empties.items():
        if sid not in by_id:
            docs_out.append(doc)
            series_out.append((np.empty(0, np.int64), np.empty(0, np.uint64)))
    return docs_out, series_out
