"""Cross-zone remote query storage over gRPC.

The reference m3query/coordinator serves its storage to OTHER coordinators
over gRPC and fans queries out to remote zones, merging results with the
local zone (/root/reference/src/query/remote/{server,client}.go, fanout in
query/storage/fanout/storage.go). This is that seam, redesigned for this
framework: raw-bytes gRPC methods (grpcio generic handlers — no protobuf
codegen) carrying hand-rolled protowire messages, with the data plane
(timestamps / IEEE-754 value bits) as little-endian raw buffers so a
million-sample response is two memcpys, not a million varints.

Wire schema (protowire field numbers):

  QueryIdsRequest:  1 namespace(utf8) 2 query_json(utf8) 3 start(varint)
                    4 end(varint) 5 limit(varint, 0=none)
  Doc:              1 series_id(bytes) 2.. repeated Field(bytes "name=value"
                    pairs as: 2 name 3 value, repeated in order)
  QueryIdsResponse: 1 repeated Doc(bytes, nested)
  ReadManyRequest:  1 namespace(utf8) 2 repeated series_id(bytes)
                    3 start(varint) 4 end(varint)
  Series:           1 times(le int64 buffer) 2 value_bits(le uint64 buffer)
  ReadManyResponse: 1 repeated Series(bytes, nested)
  LabelsRequest:    1 namespace(utf8) 2 field(bytes) 3 start 4 end
  LabelsResponse:   1 repeated value(bytes)

Timestamps are unix nanos (non-negative), so plain varints suffice.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures

import numpy as np

from contextlib import nullcontext as _nullcontext

from m3_tpu.utils.protowire import field_bytes, field_varint, iter_fields

_SERVICE = "m3.remote.Query"


def _method(name: str) -> str:
    return f"/{_SERVICE}/{name}"


# ---------------------------------------------------------------------------
# message codecs
# ---------------------------------------------------------------------------


def _clamp_ts(ns: int) -> int:
    """Varint fields are unsigned: a negative start_ns (lookback-adjusted
    PromQL start near epoch 0) would mask to a huge u64 and make remote
    zones silently return empty. No data predates the epoch, so clamp."""
    return max(0, int(ns))


def _enc_query_ids_req(namespace: str, query_json: dict, start: int, end: int,
                       limit: int | None) -> bytes:
    return (
        field_bytes(1, namespace.encode())
        + field_bytes(2, json.dumps(query_json).encode())
        + field_varint(3, _clamp_ts(start))
        + field_varint(4, _clamp_ts(end))
        + field_varint(5, limit or 0)
    )


def _dec_query_ids_req(payload: bytes):
    ns, qj, start, end, limit = "", {}, 0, 0, 0
    for fno, wt, val in iter_fields(payload):
        if fno == 1:
            ns = val.decode()
        elif fno == 2:
            qj = json.loads(val.decode())
        elif fno == 3:
            start = val
        elif fno == 4:
            end = val
        elif fno == 5:
            limit = val
    return ns, qj, start, end, (limit or None)


def _enc_doc(series_id: bytes, fields) -> bytes:
    out = field_bytes(1, series_id)
    for name, value in fields:
        out += field_bytes(2, name) + field_bytes(3, value)
    return out


def _dec_doc(payload: bytes):
    sid = b""
    names, values = [], []
    for fno, wt, val in iter_fields(payload):
        if fno == 1:
            sid = val
        elif fno == 2:
            names.append(val)
        elif fno == 3:
            values.append(val)
    return sid, tuple(zip(names, values))


def _enc_read_many_req(namespace: str, series_ids, start: int, end: int) -> bytes:
    out = field_bytes(1, namespace.encode())
    for sid in series_ids:
        out += field_bytes(2, sid)
    return out + field_varint(3, _clamp_ts(start)) + field_varint(4, _clamp_ts(end))


def _dec_read_many_req(payload: bytes):
    ns, sids, start, end = "", [], 0, 0
    for fno, wt, val in iter_fields(payload):
        if fno == 1:
            ns = val.decode()
        elif fno == 2:
            sids.append(val)
        elif fno == 3:
            start = val
        elif fno == 4:
            end = val
    return ns, sids, start, end


def _enc_series(times: np.ndarray, vbits: np.ndarray) -> bytes:
    return (
        field_bytes(1, np.asarray(times, np.int64).astype("<i8").tobytes())
        + field_bytes(2, np.asarray(vbits, np.uint64).astype("<u8").tobytes())
    )


def _dec_series(payload: bytes):
    times = np.empty(0, np.int64)
    vbits = np.empty(0, np.uint64)
    for fno, wt, val in iter_fields(payload):
        if fno == 1:
            times = np.frombuffer(val, "<i8").astype(np.int64)
        elif fno == 2:
            vbits = np.frombuffer(val, "<u8").astype(np.uint64)
    return times, vbits


def _enc_repeated(items: list[bytes]) -> bytes:
    return b"".join(field_bytes(1, it) for it in items)


def _dec_repeated(payload: bytes) -> list[bytes]:
    return [val for fno, _, val in iter_fields(payload) if fno == 1]


def _enc_labels_req(namespace: str, field: bytes, start: int, end: int) -> bytes:
    return (field_bytes(1, namespace.encode()) + field_bytes(2, field)
            + field_varint(3, _clamp_ts(start)) + field_varint(4, _clamp_ts(end)))


def _dec_labels_req(payload: bytes):
    ns, fld, start, end = "", b"", 0, 0
    for fno, wt, val in iter_fields(payload):
        if fno == 1:
            ns = val.decode()
        elif fno == 2:
            fld = val
        elif fno == 3:
            start = val
        elif fno == 4:
            end = val
    return ns, fld, start, end


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class RemoteQueryServer:
    """Serves a database (single-node Database or ClusterDatabase facade)
    to remote-zone coordinators. The reference analog registers the
    compressed-fetch gRPC service on the coordinator
    (query/remote/server.go); here the four read RPCs cover the engine's
    whole storage contract (query_ids/read_many/labels)."""

    def __init__(self, db, listen: str, max_workers: int = 8):
        import grpc

        self.db = db
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        from m3_tpu.utils.instrument import default_registry

        scope = default_registry().root_scope("remote")

        def traced(name, fn):
            # server half of cross-zone trace propagation: the client sent
            # the coordinator's context as gRPC metadata; this zone's spans
            # join that trace (and honor its sampling decision); the
            # per-method histogram feeds this zone's /metrics
            observe = scope.subscope("serve", method=name) \
                .histogram_handle("seconds")

            def call(req, ctx):
                import time as _time

                from m3_tpu.utils import trace

                tctx = trace.from_grpc_context(ctx)
                t0 = _time.perf_counter()
                try:
                    with trace.activate(tctx) if tctx is not None else \
                            _nullcontext():
                        with trace.span(f"query.remote.{name}"):
                            return fn(req, ctx)
                finally:
                    observe(_time.perf_counter() - t0)

            return call

        handlers = {
            "QueryIds": traced("query_ids", self._query_ids),
            "ReadMany": traced("read_many", self._read_many),
            "LabelNames": traced("label_names", self._labels),
            "LabelValues": traced("label_values", self._labels),
            "Health": lambda req, ctx: b"ok",
        }

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                name = details.method.rsplit("/", 1)[-1]
                fn = handlers.get(name)
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(fn)

        self._server.add_generic_rpc_handlers((_Handler(),))
        self.port = self._server.add_insecure_port(listen)
        self._server.start()

    def close(self) -> None:
        # wait for in-flight handlers: the coordinator closes the database
        # right after this, so returning early would race reads against it
        self._server.stop(grace=0.5).wait()

    # -- handlers (bytes in, bytes out) --

    def _query_ids(self, req: bytes, ctx) -> bytes:
        from m3_tpu.index.query import query_from_json

        ns_name, qj, start, end, limit = _dec_query_ids_req(req)
        ns = self.db.namespaces[ns_name]
        docs = ns.query_ids(query_from_json(qj), start, end, limit)
        return _enc_repeated([_enc_doc(d.series_id, d.fields) for d in docs])

    def _read_many(self, req: bytes, ctx) -> bytes:
        ns_name, sids, start, end = _dec_read_many_req(req)
        ns = self.db.namespaces[ns_name]
        results = ns.read_many(sids, start, end)
        return _enc_repeated([_enc_series(t, v) for t, v in results])

    def _labels(self, req: bytes, ctx) -> bytes:
        ns_name, fld, start, end = _dec_labels_req(req)
        ns = self.db.namespaces[ns_name]
        if fld:
            vals = ns.index.aggregate_field_values(fld, start, end)
        else:
            vals = ns.index.aggregate_field_names(start, end)
        return _enc_repeated(list(vals))


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RemoteZone:
    """Client for one remote zone's coordinator (query/remote/client.go
    role). Lazy channel; raw-bytes unary calls; thread-safe."""

    def __init__(self, name: str, target: str, timeout_s: float = 10.0):
        self.name = name
        self.target = target
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._channel = None
        self._stubs: dict[str, object] = {}

    def _stub(self, method: str):
        import grpc

        with self._lock:
            if self._channel is None:
                self._channel = grpc.insecure_channel(self.target)
            st = self._stubs.get(method)
            if st is None:
                st = self._channel.unary_unary(_method(method))
                self._stubs[method] = st
        return st

    def _call(self, method: str, req: bytes):
        """One unary call carrying the active trace context as metadata,
        so the remote zone's spans stitch into this coordinator's trace."""
        from m3_tpu.utils import trace

        return self._stub(method)(req, timeout=self.timeout_s,
                                  metadata=trace.grpc_metadata())

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._stubs.clear()

    # -- storage surface (per-namespace calls used by FanoutNamespace) --

    def query_ids(self, namespace: str, query_json: dict, start: int,
                  end: int, limit=None):
        resp = self._call("QueryIds", _enc_query_ids_req(
            namespace, query_json, start, end, limit))
        return [_dec_doc(d) for d in _dec_repeated(resp)]

    def read_many(self, namespace: str, series_ids, start: int, end: int):
        resp = self._call("ReadMany", _enc_read_many_req(
            namespace, series_ids, start, end))
        return [_dec_series(s) for s in _dec_repeated(resp)]

    def label_names(self, namespace: str, start: int, end: int):
        resp = self._call("LabelNames", _enc_labels_req(
            namespace, b"", start, end))
        return _dec_repeated(resp)

    def label_values(self, namespace: str, field: bytes, start: int, end: int):
        resp = self._call("LabelValues", _enc_labels_req(
            namespace, field, start, end))
        return _dec_repeated(resp)

    def healthy(self) -> bool:
        try:
            return self._stub("Health")(b"", timeout=self.timeout_s) == b"ok"
        except Exception:  # noqa: BLE001
            return False
