"""PromQL lexer + recursive-descent parser.

Role parity with the reference's PromQL front-end, which wraps the upstream
prometheus/prometheus parser (/root/reference/src/query/parser/promql/
matchers.go:28, types.go). This is an independent implementation of the
PromQL grammar: vector/matrix selectors with label matchers and offsets,
binary operators with precedence + vector matching modifiers, aggregation
operators with by/without grouping, function calls, and literals.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from m3_tpu.index.query import Matcher, MatchType

# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


class Expr:
    pass


@dataclass
class NumberLiteral(Expr):
    value: float


@dataclass
class StringLiteral(Expr):
    value: str


@dataclass
class VectorSelector(Expr):
    name: str | None
    matchers: list[Matcher]
    offset_ns: int = 0
    # @ modifier: absolute ns timestamp, or "start"/"end" (resolved by the
    # engine to the query range bounds)
    at_ns: "int | str | None" = None


@dataclass
class MatrixSelector(Expr):
    selector: VectorSelector
    range_ns: int = 0


@dataclass
class SubqueryExpr(Expr):
    """expr[range:step] — evaluate expr at step-aligned instants over the
    trailing range, yielding a range vector (upstream subquery semantics)."""

    expr: Expr
    range_ns: int
    step_ns: int | None = None  # None -> engine's default resolution
    offset_ns: int = 0
    at_ns: "int | str | None" = None


@dataclass
class Call(Expr):
    func: str
    args: list[Expr] = field(default_factory=list)


@dataclass
class VectorMatching:
    on: bool = False  # True: match on `labels`; False: ignoring `labels`
    labels: tuple[str, ...] = ()
    group_left: bool = False
    group_right: bool = False
    include: tuple[str, ...] = ()


@dataclass
class BinaryExpr(Expr):
    op: str
    lhs: Expr
    rhs: Expr
    bool_mode: bool = False
    matching: VectorMatching | None = None


@dataclass
class AggregateExpr(Expr):
    op: str
    expr: Expr
    param: Expr | None = None
    grouping: tuple[str, ...] = ()
    without: bool = False


@dataclass
class UnaryExpr(Expr):
    op: str
    expr: Expr


AGGREGATORS = {
    "sum", "avg", "min", "max", "count", "stddev", "stdvar",
    "topk", "bottomk", "quantile", "count_values", "group",
}

COMPARISONS = {"==", "!=", ">", "<", ">=", "<="}
SET_OPS = {"and", "or", "unless"}

_DURATION_UNITS = {
    "ms": 10**6,
    "s": 10**9,
    "m": 60 * 10**9,
    "h": 3600 * 10**9,
    "d": 24 * 3600 * 10**9,
    "w": 7 * 24 * 3600 * 10**9,
    "y": 365 * 24 * 3600 * 10**9,
}

_DURATION_RE = re.compile(r"(\d+)(ms|s|m|h|d|w|y)")


def parse_duration(s: str) -> int:
    """'1h30m' -> nanoseconds."""
    total = 0
    pos = 0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ParseError(f"invalid duration {s!r}")
        total += int(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or total == 0 and s != "0":
        if not (pos == len(s) and pos > 0):
            raise ParseError(f"invalid duration {s!r}")
    return total


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>\s+)
  | (?P<COMMENT>\#[^\n]*)
  | (?P<DURATION>\d+(?:ms|s|m|h|d|w|y)(?:\d+(?:ms|s|m|h|d|w|y))*)
  | (?P<NUMBER>
        0[xX][0-9a-fA-F]+
      | (?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?
      | [iI][nN][fF](?![a-zA-Z0-9_:])
      | [nN][aA][nN](?![a-zA-Z0-9_:])
    )
  | (?P<IDENT>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<OP>=~|!~|==|!=|<=|>=|<|>|\+|-|\*|/|%|\^|=|\(|\)|\{|\}|\[|\]|,|@)
    """,
    re.VERBOSE,
)


@dataclass
class Token:
    kind: str
    text: str
    pos: int


def tokenize(src: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise ParseError(f"unexpected character {src[pos]!r} at {pos}")
        kind = m.lastgroup
        if kind not in ("WS", "COMMENT"):
            out.append(Token(kind, m.group(), pos))
        pos = m.end()
    out.append(Token("EOF", "", pos))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, src: str):
        self.toks = tokenize(src)
        self.i = 0

    # -- token helpers --

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str) -> Token:
        t = self.next()
        if t.text != text:
            raise ParseError(f"expected {text!r}, got {t.text!r} at {t.pos}")
        return t

    def accept(self, text: str) -> bool:
        if self.peek().text == text:
            self.i += 1
            return True
        return False

    # -- grammar --

    def parse(self) -> Expr:
        e = self.parse_expr()
        t = self.peek()
        if t.kind != "EOF":
            raise ParseError(f"unexpected trailing input {t.text!r} at {t.pos}")
        return e

    def parse_expr(self) -> Expr:
        return self.parse_binary(0)

    _PRECEDENCE = [
        ({"or"}, False),
        ({"and", "unless"}, False),
        (COMPARISONS, False),
        ({"+", "-"}, False),
        ({"*", "/", "%"}, False),
        ({"^"}, True),  # right associative
    ]

    def parse_binary(self, level: int) -> Expr:
        if level >= len(self._PRECEDENCE):
            return self.parse_unary()
        ops, right_assoc = self._PRECEDENCE[level]
        lhs = self.parse_binary(level + 1)
        while self.peek().text in ops:
            op = self.next().text
            bool_mode = False
            if self.accept("bool"):
                bool_mode = True
            matching = self._parse_matching()
            rhs = self.parse_binary(level if right_assoc else level + 1)
            lhs = BinaryExpr(op, lhs, rhs, bool_mode, matching)
        return lhs

    def _parse_matching(self) -> VectorMatching | None:
        t = self.peek().text
        if t not in ("on", "ignoring"):
            return None
        on = self.next().text == "on"
        labels = tuple(self._parse_label_list())
        m = VectorMatching(on=on, labels=labels)
        t = self.peek().text
        if t in ("group_left", "group_right"):
            self.next()
            if t == "group_left":
                m.group_left = True
            else:
                m.group_right = True
            if self.peek().text == "(":
                m.include = tuple(self._parse_label_list())
        return m

    def _parse_label_list(self) -> list[str]:
        self.expect("(")
        labels = []
        if not self.accept(")"):
            while True:
                t = self.next()
                if t.kind != "IDENT":
                    raise ParseError(f"expected label name, got {t.text!r}")
                labels.append(t.text)
                if not self.accept(","):
                    break
            self.expect(")")
        return labels

    def parse_unary(self) -> Expr:
        t = self.peek()
        if t.text in ("+", "-"):
            self.next()
            return UnaryExpr(t.text, self.parse_unary())
        return self.parse_postfix(self.parse_atom())

    def parse_postfix(self, e: Expr) -> Expr:
        while True:
            t = self.peek()
            if t.text == "[":
                self.next()
                d = self.next()
                if d.kind not in ("DURATION", "NUMBER"):
                    raise ParseError(f"expected duration in range selector, got {d.text!r}")
                rng = parse_duration(d.text) if d.kind == "DURATION" else int(
                    float(d.text) * 1e9
                )
                pt = self.peek()
                if pt.kind == "IDENT" and pt.text.startswith(":"):
                    # subquery: expr[range:step]. The lexer folds ':' (and
                    # any attached step like ':1m') into one IDENT because
                    # colons are legal in metric names.
                    self.next()
                    rest = pt.text[1:]
                    if rest:
                        step = parse_duration(rest)
                    elif self.peek().kind == "DURATION":
                        step = parse_duration(self.next().text)
                    else:
                        step = None
                    self.expect("]")
                    e = SubqueryExpr(e, rng, step)
                    continue
                self.expect("]")
                if not isinstance(e, VectorSelector):
                    raise ParseError("range selector requires a vector selector")
                e = MatrixSelector(e, rng)
            elif t.text == "offset":
                self.next()
                d = self.next()
                neg = False
                if d.text == "-":
                    neg = True
                    d = self.next()
                if d.kind != "DURATION":
                    raise ParseError(f"expected duration after offset, got {d.text!r}")
                off = parse_duration(d.text) * (-1 if neg else 1)
                if isinstance(e, (VectorSelector, SubqueryExpr)):
                    e.offset_ns = off
                elif isinstance(e, MatrixSelector):
                    e.selector.offset_ns = off
                else:
                    raise ParseError("offset requires a selector")
            elif t.text == "@":
                self.next()
                at = self._parse_at()
                if isinstance(e, (VectorSelector, SubqueryExpr)):
                    e.at_ns = at
                elif isinstance(e, MatrixSelector):
                    e.selector.at_ns = at
                else:
                    raise ParseError("@ modifier requires a selector")
            else:
                return e

    def _parse_at(self) -> "int | str":
        """@ <unix-seconds> | @ start() | @ end()"""
        t = self.next()
        neg = False
        if t.text == "-":
            neg = True
            t = self.next()
        if t.kind == "NUMBER":
            v = float(t.text)
            return int((-v if neg else v) * 1e9)
        if t.kind == "IDENT" and t.text in ("start", "end") and not neg:
            self.expect("(")
            self.expect(")")
            return t.text
        raise ParseError(f"expected timestamp, start() or end() after @, got {t.text!r}")

    def parse_atom(self) -> Expr:
        t = self.peek()
        if t.text == "(":
            self.next()
            e = self.parse_expr()
            self.expect(")")
            return e
        if t.kind == "NUMBER":
            self.next()
            return NumberLiteral(_parse_number(t.text))
        if t.kind == "STRING":
            self.next()
            return StringLiteral(_unquote(t.text))
        if t.text == "{":
            return self._parse_vector_selector(None)
        if t.kind == "IDENT":
            name = self.next().text
            if name in AGGREGATORS and self.peek().text in ("(", "by", "without"):
                return self._parse_aggregate(name)
            if self.peek().text == "(":
                return self._parse_call(name)
            return self._parse_vector_selector(name)
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    def _parse_call(self, name: str) -> Call:
        self.expect("(")
        args = []
        if not self.accept(")"):
            while True:
                args.append(self.parse_expr())
                if not self.accept(","):
                    break
            self.expect(")")
        return Call(name, args)

    def _parse_aggregate(self, op: str) -> AggregateExpr:
        grouping: tuple[str, ...] = ()
        without = False
        if self.peek().text in ("by", "without"):
            without = self.next().text == "without"
            grouping = tuple(self._parse_label_list())
        self.expect("(")
        first = self.parse_expr()
        param = None
        expr = first
        if self.accept(","):
            param = first
            expr = self.parse_expr()
        self.expect(")")
        if self.peek().text in ("by", "without"):
            without = self.next().text == "without"
            grouping = tuple(self._parse_label_list())
        return AggregateExpr(op, expr, param, grouping, without)

    def _parse_vector_selector(self, name: str | None) -> VectorSelector:
        matchers: list[Matcher] = []
        if name is not None:
            matchers.append(Matcher(MatchType.EQUAL, b"__name__", name.encode()))
        if self.peek().text == "{":
            self.next()
            if not self.accept("}"):
                while True:
                    lt = self.next()
                    if lt.kind not in ("IDENT",) and lt.text not in SET_OPS:
                        raise ParseError(f"expected label name, got {lt.text!r}")
                    op = self.next().text
                    try:
                        mt = MatchType(op)
                    except ValueError:
                        raise ParseError(f"invalid matcher operator {op!r}") from None
                    vt = self.next()
                    if vt.kind != "STRING":
                        raise ParseError(f"expected quoted label value, got {vt.text!r}")
                    matchers.append(Matcher(mt, lt.text.encode(), _unquote(vt.text).encode()))
                    if not self.accept(","):
                        break
                self.expect("}")
        if not matchers:
            raise ParseError("vector selector must have at least one matcher")
        return VectorSelector(name, matchers)


def _parse_number(text: str) -> float:
    t = text.lower()
    if t.startswith("0x"):
        return float(int(text, 16))
    if t == "inf":
        return float("inf")
    if t == "nan":
        return float("nan")
    return float(text)


_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"', "'": "'",
    "a": "\a", "b": "\b", "f": "\f", "v": "\v", "0": "\0",
}

_ESCAPE_RE = re.compile(
    r"\\(?:x([0-9a-fA-F]{2})|u([0-9a-fA-F]{4})|U([0-9a-fA-F]{8})|(.))",
    re.DOTALL,
)


def _unquote(s: str) -> str:
    """Go-style string unescaping, UTF-8 safe (no latin-1 round trip)."""
    body = s[1:-1]

    def sub(m: re.Match) -> str:
        if m.group(1):
            return chr(int(m.group(1), 16))
        if m.group(2):
            return chr(int(m.group(2), 16))
        if m.group(3):
            return chr(int(m.group(3), 16))
        c = m.group(4)
        return _ESCAPES.get(c, c)

    return _ESCAPE_RE.sub(sub, body)


def parse(src: str) -> Expr:
    """Parse a PromQL expression."""
    return Parser(src).parse()
