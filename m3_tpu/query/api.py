"""Coordinator HTTP API.

Role parity with the reference coordinator surface
(/root/reference/src/query/api/v1/httpd/handler.go:175-247): Prometheus
remote write (snappy+protobuf), query/query_range, labels, label values,
series, plus a JSON debug-write endpoint and health/ready. Runs on the
stdlib threading HTTP server; each ingest batch lands through the same
Database write path the TPU ingest pipeline uses.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from m3_tpu.index.query import Matcher, MatchType, matchers_to_query
from m3_tpu.query.engine import Engine, QueryLimitError, Scalar, Vector
from m3_tpu.query.windows import NS
from m3_tpu.utils import faults, protowire, snappy
from m3_tpu.utils.tenantlimits import TenantShedError

_MATCH_TYPE_BY_PROM = {
    0: MatchType.EQUAL,
    1: MatchType.NOT_EQUAL,
    2: MatchType.REGEXP,
    3: MatchType.NOT_REGEXP,
}

def _parse_time(s: str) -> int:
    """Prometheus API time (unix seconds float or RFC3339) -> ns."""
    try:
        return int(float(s) * NS)
    except ValueError:
        pass
    import datetime as dt

    t = dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    return int(t.timestamp() * NS)


def _parse_graphite_time(s: str, now_ns: int) -> int:
    """Graphite from/until: epoch seconds, 'now', or relative '-1h'."""
    if s == "now":
        return now_ns
    if s.startswith("-") or s.startswith("+"):
        from m3_tpu.metrics.policy import parse_go_duration

        mag = parse_go_duration(s.lstrip("+-"))
        return now_ns - mag if s.startswith("-") else now_ns + mag
    return _parse_time(s)


def _parse_step(s: str) -> int:
    try:
        return int(float(s) * NS)
    except ValueError:
        from m3_tpu.query.promql import parse_duration

        return parse_duration(s)


def _parse_series_selector(sel: str) -> list[Matcher]:
    """'metric{a="b",c!~"d"}' -> matchers (for /series and remote read)."""
    from m3_tpu.query.promql import Parser

    p = Parser(sel)
    vs = p.parse_atom()
    from m3_tpu.query.promql import VectorSelector

    if not isinstance(vs, VectorSelector) or p.peek().kind != "EOF":
        raise ValueError(f"invalid series selector {sel!r}")
    return vs.matchers


def _parse_influx_line(line: bytes):
    """'measurement,tag=v field=1.5,other=2i 1600000000000000000' ->
    (measurement, [(k, v)], [(field, float)], t_ns|None), or None."""
    try:
        # split on unescaped spaces: sections = ident, fields, [timestamp]
        sections = _split_unescaped(line, b" ")
        if len(sections) < 2:
            return None
        ident_parts = _split_unescaped(sections[0], b",")
        measurement = _influx_unescape(ident_parts[0])
        tags = []
        for part in ident_parts[1:]:
            k, _, v = part.partition(b"=")
            tags.append((_influx_unescape(k), _influx_unescape(v)))
        fields = []
        field_errors = 0
        for part in _split_unescaped(sections[1], b","):
            k, _, v = part.partition(b"=")
            try:
                if v.endswith(b"i") or v.endswith(b"u"):
                    fv = float(int(v[:-1]))
                elif v in (b"t", b"T", b"true", b"True"):
                    fv = 1.0
                elif v in (b"f", b"F", b"false", b"False"):
                    fv = 0.0
                elif v.startswith(b'"'):
                    continue  # string fields have no numeric representation
                else:
                    fv = float(v)
            except ValueError:
                field_errors += 1  # one bad field must not drop the line
                continue
            fields.append((_influx_unescape(k), fv))
        if not fields:
            return None
        t_ns = int(sections[2]) if len(sections) > 2 else None
        return measurement, sorted(tags), fields, t_ns, field_errors
    except (ValueError, IndexError):
        return None


def _split_unescaped(raw: bytes, sep: bytes) -> list[bytes]:
    """Split on sep outside escapes AND outside double-quoted strings
    (string field values may contain commas/spaces)."""
    out = []
    cur = bytearray()
    i = 0
    in_quotes = False
    while i < len(raw):
        c = raw[i:i + 1]
        if c == b"\\" and i + 1 < len(raw):
            cur += raw[i:i + 2]
            i += 2
            continue
        if c == b'"':
            in_quotes = not in_quotes
            cur += c
        elif c == sep and not in_quotes:
            out.append(bytes(cur))
            cur = bytearray()
        else:
            cur += c
        i += 1
    out.append(bytes(cur))
    return [p for p in out if p]


def _influx_unescape(raw: bytes) -> bytes:
    return raw.replace(b"\\,", b",").replace(b"\\ ", b" ").replace(b"\\=", b"=")


def _fmt_value(v: float) -> str:
    if np.isnan(v):
        return "NaN"
    if np.isposinf(v):
        return "+Inf"
    if np.isneginf(v):
        return "-Inf"
    return repr(float(v))


def _wants_openmetrics(q, headers) -> bool:
    """Scrape-format selection shared by the coordinator and node
    /metrics endpoints: EXPLICIT `?format=openmetrics` only. The
    exemplar exposition keeps the PR-4 family names (counters without
    the `_total` suffix OpenMetrics mandates) so `_m3_system` series and
    dashboards line up across formats — which means a stock Prometheus
    scraper, whose default Accept header advertises openmetrics-text,
    must keep getting the always-valid text/plain 0.0.4 render unless an
    operator opts this scrape in."""
    fmt = (q.get("format", [""])[0] if q else "").lower()
    return fmt in ("openmetrics", "openmetrics-text")


def _render_metrics(q, headers):
    """(status, content_type, payload) for a /metrics scrape: OpenMetrics
    with exemplars when negotiated, strict Prometheus text otherwise."""
    from m3_tpu.utils.instrument import default_registry

    reg = default_registry()
    if _wants_openmetrics(q, headers):
        return (200,
                "application/openmetrics-text; version=1.0.0; charset=utf-8",
                reg.render_openmetrics())
    return 200, "text/plain; version=0.0.4", reg.render_prometheus()


class CoordinatorAPI:
    """HTTP facade over a Database + PromQL Engine."""

    def __init__(self, db, namespace: str = "default", limits=None,
                 query_compile: bool = False):
        self.db = db
        self.namespace = namespace
        # whole-query compilation default for every engine this API
        # builds (config `query: compile:`; M3_TPU_QUERY_COMPILE is the
        # per-process escape hatch either way)
        self.query_compile = bool(query_compile)
        self.engine = Engine(db, namespace, limits=limits,
                             query_compile=self.query_compile)
        self._server: ThreadingHTTPServer | None = None
        # optional DownsamplerAndWriter: ingest then fans out through the
        # embedded downsampler (coordinator service wiring)
        self.writer = None
        # optional AdminAPI (namespace/placement/topic CRUD; query/admin.py)
        self.admin = None
        # optional per-tenant admission controller (utils/tenantlimits,
        # coordinator service wiring): None = no quotas, zero overhead
        self.admission = None
        # per-tenant request-latency observer handles, keyed by BOUNDED
        # label (configured tenants + the default namespace + "other")
        self._tenant_observers: dict[str, object] = {}
        # per-namespace engine cache for ?namespace= query routing (the
        # self-monitoring loop's _m3_system namespace is queried this way)
        self._engines: dict[str, Engine] = {namespace: self.engine}
        self._engines_lock = threading.Lock()
        from m3_tpu.utils.instrument import default_registry

        self._scope = default_registry().root_scope("coordinator")

    # bound on cached per-namespace engines: namespaces are operator-
    # created (bounded), but the ?namespace= value is client-supplied
    MAX_ENGINES = 64

    def _engine_for(self, namespace: str) -> Engine:
        # validate before caching: an unknown namespace must not grow the
        # cache (fanout facades union remote zones, so remote-only names
        # are only checkable there at query time — they still pass)
        if namespace != self.namespace \
                and namespace not in self.db.namespaces \
                and not getattr(self.db, "zones", None):
            raise ValueError(f"unknown namespace {namespace!r}")
        with self._engines_lock:
            eng = self._engines.get(namespace)
            if eng is None:
                if len(self._engines) >= self.MAX_ENGINES:
                    # drop an arbitrary non-default entry (engines are
                    # cheap to rebuild; correctness never depends on one)
                    for key in list(self._engines):
                        if key != self.namespace:
                            del self._engines[key]
                            break
                eng = self._engines[namespace] = Engine(
                    self.db, namespace, query_compile=self.query_compile)
        return eng

    def _write(self, name: bytes, tags, t_ns: int, value: float):
        if self.writer is not None:
            from m3_tpu.metrics.aggregation import MetricType

            return self.writer.write(MetricType.GAUGE, name, tags, t_ns, value)
        return self.db.write_tagged(self.namespace, name, list(tags), t_ns, value)

    # -- request handling --

    def handle(self, method: str, path: str, query: dict, body: bytes,
               headers=None):
        """Returns (status, content_type, payload, headers) — routes may
        return the legacy 3-tuple; headers default to {}.

        Trace ingress: the head-based sampling decision for the whole
        request is made HERE (or honored from a propagated `traceparent`
        in `headers`), every downstream hop — engine, session, storage
        nodes — follows it, and the response echoes the trace id in an
        `M3-Trace-Id` header so a slow query is one /debug/traces lookup
        away."""
        import math
        import time as _time

        from m3_tpu.utils import trace

        # one resource budget per request, enforced in the storage read
        # path (covers PromQL, Graphite render, and remote read alike)
        limits = getattr(self.db, "limits", None)
        ctx = trace.start_request(headers)
        t0 = _time.perf_counter()
        try:
            if limits is not None:
                limits.start_query()
            with trace.activate(ctx), \
                    trace.span(trace.API_REQUEST, path=path, method=method), \
                    self._scope.histogram("request_seconds"):
                res = self._route(method, path, query, body, headers)
            status, ctype, payload, hdrs = res if len(res) == 4 \
                else (*res, {})
        except TenantShedError as e:
            # per-tenant admission shed: 429 + Retry-After, the
            # degrade-THIS-tenant contract (clients treat it as
            # backpressure, never as a node failure)
            status, ctype, payload, hdrs = 429, "application/json", json.dumps(
                {"status": "error", "errorType": "tenant_limit",
                 "tenant": e.namespace, "kind": e.kind,
                 "retry_after_s": round(e.retry_after_s, 3),
                 "error": str(e)}
            ).encode(), {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))}
        except faults.SimulatedCrash:
            # crash semantics match the node API: never served as an
            # error envelope — the request thread dies (and with
            # M3_TPU_FAULTS_EXIT=1 armed, the whole process does)
            faults.escalate()
            raise
        except QueryLimitError as e:
            status, ctype, payload, hdrs = 422, "application/json", json.dumps(
                {"status": "error", "errorType": "query_limit", "error": str(e)}
            ).encode(), {}
        except Exception as e:  # surface as prometheus-style error envelope
            status, ctype, payload, hdrs = 400, "application/json", json.dumps(
                {"status": "error", "errorType": "bad_data", "error": str(e)}
            ).encode(), {}
        finally:
            if limits is not None:
                limits.end_query()
        if path.startswith("/api/v1/") or path == "/render":
            # bytes-on-wire ledger for the coordinator's egress (the
            # `response` flow of net_bytes_{sent,recv}): only query-serving
            # routes — a /metrics scrape reporting its own response bytes
            # would feed back into itself
            from m3_tpu.utils import wire

            wire.account("response", sent=len(payload),
                         recv=len(body) if body else 0)
            if self.admission is not None:
                # only tenant-billable routes feed the per-tenant latency
                # histogram: /metrics scrapes, health polls and /debug would
                # dilute the p99 the isolation SLO is asserted against
                self._observe_tenant(query, _time.perf_counter() - t0)
        if trace.default_tracer().enabled:
            hdrs = {**hdrs, "M3-Trace-Id": ctx.trace_id}
        return status, ctype, payload, hdrs

    # -- per-tenant admission plumbing --

    def _tenant_of(self, q) -> str:
        """The tenant (== namespace) a request bills to: ?namespace= on
        query routes, the configured ingest namespace otherwise."""
        return (q.get("namespace", [self.namespace])[0] if q
                else self.namespace)

    def _observe_tenant(self, q, seconds: float) -> None:
        """Per-tenant request-latency histogram (the PR-4 family,
        namespace-labelled): the substrate for isolation SLOs — tenant
        B's p99 must hold while tenant A is being shed. Cardinality is
        bounded: only configured tenants and the default namespace get
        their own label, everything else shares "other"."""
        ns = self._tenant_of(q)
        if ns != self.namespace and not self.admission.is_configured(ns):
            ns = "other"
        obs = self._tenant_observers.get(ns)
        if obs is None:
            obs = self._scope.subscope("tenant", namespace=ns) \
                .histogram_handle("request_seconds")
            self._tenant_observers[ns] = obs
        obs(seconds)

    def _admit_write(self, datapoints: int) -> None:
        """Ingest gate: raises TenantShedError (-> 429) when the tenant
        is over its datapoints/sec rate or live-cardinality ceiling."""
        if self.admission is not None and datapoints:
            self.admission.admit_write(self.namespace, datapoints)

    def _admit_query(self, ns: str) -> None:
        """Query gate: queries/sec bucket + post-paid cost budget."""
        if self.admission is not None:
            self.admission.admit_query(ns)

    def _charge_query(self, ns: str, engine) -> None:
        """Bill the finished query's QueryStats against the tenant's
        cost budget (post-paid; never raises)."""
        if self.admission is not None:
            self.admission.charge_query_cost(
                ns, getattr(engine, "last_stats", None))

    def _warning_headers(self, engine=None) -> dict:
        """PR-2 partial-result contract, threaded out to HTTP: one
        M3-Warnings header value per degraded read leg (failed session
        host, skipped fanout zone) recorded by the engine for THIS query.
        An absent header means the result is complete."""
        warns = getattr(engine or self.engine, "last_warnings", None)
        if not warns:
            return {}
        return {"M3-Warnings": ",".join(str(w) for w in warns)}

    def _route(self, method, path, q, body, headers=None):
        if path == "/health":
            return 200, "application/json", b'{"ok":true}'
        if path == "/ready":
            # ready == the storage below is open/bootstrapped
            ready = bool(getattr(self.db, "_open", True))
            return (200 if ready else 503), "application/json", json.dumps(
                {"ready": ready}
            ).encode()
        if self.admin is not None and (
            path.startswith("/api/v1/services/")
            or path.startswith("/api/v1/database/")
            or path.startswith("/api/v1/topic")
            or path == "/api/v1/runtime"
            or path == "/api/v1/rules"
            or path.startswith("/api/v1/rules/")
        ):
            res = self.admin.handle(method, path, q, body)
            if res is not None:
                status, payload = res
                return status, "application/json", payload
        if path == "/metrics":
            return _render_metrics(q, headers)
        if path == "/debug/dump":
            return self._debug_dump()
        if path == "/debug/profile":
            # the always-on profiling & saturation plane: sampling
            # profiler top-N / collapsed stacks, contended-lock table,
            # stall-watchdog status (utils/profiler; POST toggles live)
            from m3_tpu.utils import profiler

            status, payload, ctype = profiler.handle_debug_profile(
                method, q, body)
            return status, ctype, payload
        if path == "/debug/compute":
            # the device-compute observability plane: top-N programs by
            # device time, plan-cache occupancy, padding-waste ledger,
            # device-resident cache bytes (utils/compute_stats)
            from m3_tpu.utils import compute_stats

            status, payload, ctype = compute_stats.handle_debug_compute(
                method, q, body)
            return status, ctype, payload
        if path == "/debug/traces":
            return self._debug_traces(method, q, body)
        if path == "/debug/explain":
            from m3_tpu.query import explain as explain_mod

            trace_id = q.get("trace_id", [None])[0]
            if trace_id:
                return 200, "application/json", json.dumps(
                    {"plans": explain_mod.find(trace_id)}).encode()
            limit = int(q.get("limit", ["20"])[0])
            return 200, "application/json", json.dumps(
                {"plans": explain_mod.recent(limit)}).encode()
        if path == "/debug/standing":
            # per-rule standing-query evaluation state (watermarks, eval/
            # skip tallies, matched shards, last error) — the rig's
            # standing_rules episode audits recovery through this surface
            standing = getattr(getattr(self.writer, "downsampler", None),
                               "standing", None)
            if standing is None:
                return 404, "application/json", json.dumps(
                    {"status": "error", "error": "no standing rules"}
                ).encode()
            return 200, "application/json", json.dumps(
                standing.status()).encode()
        if path == "/debug/slow_queries":
            from m3_tpu.utils import querystats

            limit = int(q.get("limit", ["50"])[0])
            return 200, "application/json", json.dumps(
                {"queries": querystats.slow_queries(limit),
                 "threshold_ms": round(querystats.threshold_s() * 1e3, 3)}
            ).encode()
        if path == "/api/v1/prom/remote/write" and method == "POST":
            return self._remote_write(body)
        if path == "/api/v1/prom/remote/read" and method == "POST":
            return self._remote_read(body)
        if path == "/api/v1/json/write" and method == "POST":
            return self._json_write(body)
        if path == "/api/v1/influxdb/write" and method == "POST":
            return self._influx_write(q, body)
        if path == "/api/v1/query_range":
            return self._query_range(q)
        if path == "/api/v1/m3ql/query_range":
            return self._m3ql_query_range(q)
        if path == "/api/v1/query":
            return self._query_instant(q)
        if path == "/api/v1/labels":
            return self._labels(q)
        m = re.fullmatch(r"/api/v1/label/([^/]+)/values", path)
        if m:
            return self._label_values(m.group(1), q)
        if path == "/api/v1/series":
            return self._series(q)
        if path == "/render":
            return self._graphite_render(q)
        if path == "/metrics/find":
            return self._graphite_find(q)
        return 404, "application/json", json.dumps(
            {"status": "error", "error": f"unknown path {path}"}
        ).encode()

    def _debug_traces(self, method, q, body: bytes):
        """GET: recent spans, or — with ?trace_id= — the ONE stitched
        cross-process tree for that trace: local ring spans merged with
        every storage node's (cluster session connections expose
        /debug/traces on the node API). POST: runtime toggle
        ({"enabled": bool, "sample_every": int})."""
        from m3_tpu.utils import trace

        tracer = trace.default_tracer()
        if method == "POST":
            doc = json.loads(body or b"{}")
            if "enabled" in doc:
                tracer.enabled = bool(doc["enabled"])
            if "sample_every" in doc:
                tracer.sample_every = max(1, int(doc["sample_every"]))
            return 200, "application/json", json.dumps(
                {"enabled": tracer.enabled,
                 "sample_every": tracer.sample_every}
            ).encode()
        trace_id = q.get("trace_id", [None])[0]
        if not trace_id:
            limit = int(q.get("limit", ["200"])[0])
            return 200, "application/json", json.dumps(
                {"spans": tracer.recent(limit)}
            ).encode()
        spans = tracer.find(trace_id)
        # cluster mode: gather the nodes' halves of the trace (their spans
        # live in their own process rings)
        session = getattr(self.db, "session", None)
        for host, conn in (getattr(session, "connections", None) or {}).items():
            fetch = getattr(conn, "debug_traces", None)
            if fetch is None:
                continue
            try:
                spans.extend(fetch(trace_id))
            except Exception:  # noqa: BLE001 - a dead node must not hide
                continue      # the rest of the trace
        # dedupe by span id: in-process test topologies (and co-located
        # services) share one ring, so the same span can arrive twice
        seen: set[str] = set()
        unique = []
        for s in spans:
            sid = s.get("span_id") or ""
            if sid and sid in seen:
                continue
            seen.add(sid)
            unique.append(s)
        spans = sorted(unique, key=lambda s: s.get("start_unix_ns", 0))
        return 200, "application/json", json.dumps(
            {"trace_id": trace_id, "count": len(spans), "spans": spans,
             "tree": trace.build_tree(spans)}
        ).encode()

    def _debug_dump(self):
        """Thread stacks + namespace stats (the x/debug zip-dump role)."""
        import sys
        import traceback

        stacks = {}
        for tid, frame in sys._current_frames().items():
            stacks[str(tid)] = traceback.format_stack(frame)
        ns_stats = {}
        for name, ns in list(self.db.namespaces.items()):
            shards = getattr(ns, "shards", None)
            if shards is None:  # cluster facade: nodes own the storage
                ns_stats[name] = {"remote": True}
                continue
            ns_stats[name] = {
                "shards": len(shards),
                "series": sum(s.buffer.n_series for s in shards.values()),
                "flushed_blocks": sum(
                    len(s._filesets) for s in shards.values()
                ),
            }
        return 200, "application/json", json.dumps(
            {"threads": stacks, "namespaces": ns_stats}
        ).encode()

    # -- graphite --

    def _graphite_render(self, q):
        from m3_tpu.query.graphite import GraphiteEngine

        self._admit_query(self.namespace)
        now = time.time_ns()
        start = _parse_graphite_time(q["from"][0], now) if "from" in q else now - 24 * 3600 * NS
        end = _parse_graphite_time(q["until"][0], now) if "until" in q else now
        step = 60 * NS
        if "maxDataPoints" in q:
            mdp = max(int(q["maxDataPoints"][0]), 1)
            step = max((end - start) // mdp, 10 * NS)
            step -= step % (10 * NS) or 0
            step = max(step, 10 * NS)
        eng = GraphiteEngine(self.db, self.namespace)
        out = []
        for target in q.get("target", []):
            for s in eng.render(target, start, end, step):
                out.append(
                    {
                        "target": s.name.decode(),
                        "datapoints": [
                            [None if np.isnan(v) else float(v), int(t // NS)]
                            for t, v in zip(s.times, s.values)
                        ],
                    }
                )
        return 200, "application/json", json.dumps(out).encode()

    def _graphite_find(self, q):
        from m3_tpu.query.graphite import path_prefix_query

        pattern = q["query"][0]
        ns, start, end = self._time_range(q)
        parts = pattern.split(".")
        depth = len(parts) - 1
        docs = ns.query_ids(path_prefix_query(pattern), start, end)
        name_tag = f"__g{depth}__".encode()
        deeper_tag = f"__g{depth + 1}__".encode()
        # a node can be BOTH a leaf (series ends here) and a branch
        nodes: dict[bytes, set] = {}
        for doc in docs:
            fields = dict(doc.fields)
            text = fields.get(name_tag)
            if text is None:
                continue
            kind = "branch" if deeper_tag in fields else "leaf"
            nodes.setdefault(text, set()).add(kind)
        out = []
        prefix = ".".join(parts[:-1])
        for text in sorted(nodes):
            node_id = (prefix + "." if prefix else "") + text.decode()
            for kind in sorted(nodes[text]):
                is_branch = kind == "branch"
                out.append(
                    {
                        "text": text.decode(),
                        "id": node_id,
                        "leaf": 0 if is_branch else 1,
                        "expandable": 1 if is_branch else 0,
                        "allowChildren": 1 if is_branch else 0,
                    }
                )
        return 200, "application/json", json.dumps(out).encode()

    # -- ingest --

    def _remote_write(self, body: bytes):
        payload = snappy.decompress(body)
        series = protowire.decode_write_request(payload)
        entries = []
        for ts in series:
            name = b""
            tags = []
            for k, v in ts.labels:
                if k == b"__name__":
                    name = v
                else:
                    tags.append((k, v))
            for ts_ms, value in ts.samples:
                entries.append((name, tags, ts_ms * 1_000_000, value))
        self._admit_write(len(entries))
        batch = getattr(self.db, "write_batch", None)
        if self.writer is None and batch is not None:
            # no downsampler rules to run per-sample: one op-batched
            # request per storage node (host-queue batching role) with
            # PER-ENTRY results — one sub-consistency sample degrades its
            # own slot, and the response names the shortfall instead of
            # failing (or silently acking) the whole batch
            results = batch(self.namespace, entries)
            bad = [r for r in results if r is not None]
            n = len(results) - len(bad)
            if bad:
                return 500, "application/json", json.dumps(
                    {"status": "error", "errorType": "partial_write",
                     "samples": n, "failed": len(bad),
                     "error": f"{len(bad)}/{len(results)} samples failed "
                              f"(first: {bad[0]})"}
                ).encode()
        else:
            for name, tags, t_ns, value in entries:
                self._write(name, tags, t_ns, value)
            n = len(entries)
        return 200, "application/json", json.dumps({"status": "success", "samples": n}).encode()

    def _json_write(self, body: bytes):
        doc = json.loads(body)
        tags = [(k.encode(), v.encode()) for k, v in sorted(doc.get("tags", {}).items())]
        name = doc.get("metric", "").encode()
        t_ns = int(doc["timestamp"] * NS) if "timestamp" in doc else None
        if t_ns is None:
            import time

            t_ns = time.time_ns()
        self._admit_write(1)
        self._write(name, tags, t_ns, float(doc["value"]))
        return 200, "application/json", b'{"status":"success"}'

    def _influx_write(self, q, body: bytes):
        """InfluxDB line protocol ingest (the reference influxdb handler,
        api/v1/handler/influxdb/write.go): each field of a line becomes a
        series named measurement_field, tags become labels."""
        import gzip

        if body[:2] == b"\x1f\x8b":
            body = gzip.decompress(body)
        precision = q.get("precision", ["ns"])[0]
        mult = {"ns": 1, "u": 10**3, "us": 10**3, "ms": 10**6,
                "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9}.get(precision)
        if mult is None:
            return 400, "application/json", json.dumps(
                {"status": "error", "error": f"invalid precision {precision!r}"}
            ).encode()
        n = 0
        errors = 0
        # parse the whole payload BEFORE writing: the admission gate needs
        # the datapoint count, and a shed must reject the batch without
        # having half-applied it
        writes = []
        for line in body.splitlines():
            line = line.strip()
            if not line or line.startswith(b"#"):
                continue
            parsed = _parse_influx_line(line)
            if parsed is None:
                errors += 1
                continue
            measurement, tags, fields, t_ns, field_errors = parsed
            errors += field_errors
            if t_ns is None:
                t_ns = time.time_ns()
            else:
                t_ns *= mult
            for fname, fval in fields:
                name = measurement + b"_" + fname if fname != b"value" else measurement
                writes.append((name, tags, t_ns, fval))
        self._admit_write(len(writes))
        for name, tags, t_ns, fval in writes:
            self._write(name, tags, t_ns, fval)
            n += 1
        if errors:
            # influx-style partial-write semantics: good points ARE
            # written; the client still learns something was dropped
            return 400, "application/json", json.dumps(
                {"status": "error",
                 "error": f"partial write: {errors} unparseable "
                          f"lines/fields, {n} points written"}
            ).encode()
        return 204, "application/json", b""

    # -- read --

    def _remote_read(self, body: bytes):
        self._admit_query(self.namespace)
        queries = protowire.decode_read_request(snappy.decompress(body))
        results = []
        for q in queries:
            matchers = [
                Matcher(_MATCH_TYPE_BY_PROM[m.type], m.name, m.value)
                for m in q.matchers
            ]
            res = self.db.query(
                self.namespace, matchers, q.start_ms * 1_000_000,
                q.end_ms * 1_000_000 + 1,
            )
            out = []
            for sid, fields, dps in res:
                out.append(
                    protowire.PromTimeSeries(
                        labels=sorted(fields),
                        samples=[(d.timestamp_ns // 1_000_000, d.value) for d in dps],
                    )
                )
            results.append(out)
        payload = snappy.compress(protowire.encode_read_response(results))
        return 200, "application/x-protobuf", payload

    def _query_engine(self, q) -> Engine:
        """Engine for the request's ?namespace= (default: the configured
        one) — how PromQL reaches the `_m3_system` self-monitoring tier."""
        ns = q.get("namespace", [self.namespace])[0]
        return self._engine_for(ns)

    @staticmethod
    def _explain_mode(q) -> bool | None:
        """?explain= → None (off), False (plan only), True (analyze)."""
        raw = (q.get("explain", [""])[0] or "").lower()
        if not raw:
            return None
        if raw == "analyze":
            return True
        if raw in ("plan", "true", "1"):
            return False
        raise ValueError(f"explain must be 'plan' or 'analyze', got {raw!r}")

    @staticmethod
    def _precision_of(q) -> str | None:
        """?precision=bf16 — the per-query grant for the hot tier's
        reduced-precision value mirror (storage/hottier). Anything else
        than the explicit opt-in keeps full precision."""
        raw = (q.get("precision", [""])[0] or "").lower()
        if not raw:
            return None
        if raw == "bf16":
            return "bf16"
        raise ValueError(f"precision must be 'bf16', got {raw!r}")

    def _run_explained(self, q, engine, run):
        """Run one engine evaluation, collecting its plan tree when
        ?explain= asks for one. Returns ((result, eval_ts), plan_doc) —
        plan_doc is None without explain; with it, the finished record
        (tree + trace id + envelope-parity stats) also lands in the
        /debug/explain ring."""
        from m3_tpu.storage import hottier

        base_run = run
        precision = self._precision_of(q)
        if precision is not None:
            def run():  # noqa: F811 - deliberate wrap
                with hottier.negotiated_precision(precision):
                    return base_run()
        mode = self._explain_mode(q)
        if mode is None:
            return run(), None
        from m3_tpu.query import explain as explain_mod

        with explain_mod.collect(analyze=mode) as col:
            out = run()
        doc = col.to_dict()
        st = engine.last_stats
        if st is not None:
            doc["query"] = st.query
            doc["trace_id"] = st.trace_id
            if mode:
                doc["stats"] = st.to_dict()
        explain_mod.remember(doc)
        return out, doc

    def _query_range(self, q):
        expr = q["query"][0]
        start = _parse_time(q["start"][0])
        end = _parse_time(q["end"][0])
        step = _parse_step(q["step"][0])
        self._admit_query(self._tenant_of(q))
        engine = self._query_engine(q)
        (result, eval_ts), plan = self._run_explained(
            q, engine, lambda: engine.query_range(expr, start, end, step))
        self._charge_query(self._tenant_of(q), engine)
        return (200, "application/json",
                self._render(result, eval_ts, matrix=True, engine=engine,
                             explain_doc=plan),
                self._warning_headers(engine))

    def _m3ql_query_range(self, q):
        """M3QL pipe-syntax range query (the reference's experimental
        /api/v1/m3ql endpoint role): parse with query.m3ql into the SAME
        AST and evaluate on the shared engine."""
        from m3_tpu.query import m3ql

        raw = q["query"][0]
        expr = m3ql.parse(raw)
        start = _parse_time(q["start"][0])
        end = _parse_time(q["end"][0])
        step = _parse_step(q["step"][0])
        self._admit_query(self._tenant_of(q))
        engine = self._query_engine(q)
        (result, eval_ts), plan = self._run_explained(
            q, engine, lambda: engine.query_range_expr(
                expr, start, end, step, query_text=raw))
        self._charge_query(self._tenant_of(q), engine)
        return (200, "application/json",
                self._render(result, eval_ts, matrix=True, engine=engine,
                             explain_doc=plan),
                self._warning_headers(engine))

    def _query_instant(self, q):
        expr = q["query"][0]
        t = _parse_time(q["time"][0]) if "time" in q else None
        if t is None:
            import time as _time

            t = _time.time_ns()
        self._admit_query(self._tenant_of(q))
        engine = self._query_engine(q)
        (result, eval_ts), plan = self._run_explained(
            q, engine, lambda: engine.query_instant(expr, t))
        self._charge_query(self._tenant_of(q), engine)
        return (200, "application/json",
                self._render(result, eval_ts, matrix=False, engine=engine,
                             explain_doc=plan),
                self._warning_headers(engine))

    def _render(self, result, eval_ts, matrix: bool, engine=None,
                explain_doc=None):
        ts_sec = eval_ts.astype(np.float64) / NS
        if isinstance(result, Scalar):
            if matrix:
                data = {
                    "resultType": "matrix",
                    "result": [
                        {
                            "metric": {},
                            "values": [
                                [t, _fmt_value(v)]
                                for t, v in zip(ts_sec, result.values)
                                if not np.isnan(v)
                            ],
                        }
                    ],
                }
            else:
                data = {
                    "resultType": "scalar",
                    "result": [ts_sec[0], _fmt_value(result.values[0])],
                }
        elif isinstance(result, Vector):
            if matrix:
                out = []
                for i, lb in enumerate(result.labels):
                    values = [
                        [t, _fmt_value(v)]
                        for t, v in zip(ts_sec, result.values[i])
                        if not np.isnan(v)
                    ]
                    if values:
                        out.append(
                            {
                                "metric": {
                                    k.decode(): v.decode() for k, v in lb.items()
                                },
                                "values": values,
                            }
                        )
                data = {"resultType": "matrix", "result": out}
            else:
                out = []
                for i, lb in enumerate(result.labels):
                    v = result.values[i, 0]
                    if not np.isnan(v):
                        out.append(
                            {
                                "metric": {
                                    k.decode(): val.decode() for k, val in lb.items()
                                },
                                "value": [ts_sec[0], _fmt_value(v)],
                            }
                        )
                data = {"resultType": "vector", "result": out}
        else:
            data = {"resultType": "string", "result": [ts_sec[0], result.value]}
        doc = {"status": "success", "data": data}
        engine = engine or self.engine
        # prometheus envelope convention: a top-level "warnings" list
        # accompanies a SUCCEEDING partial result (mirrors M3-Warnings)
        warns = getattr(engine, "last_warnings", None)
        if warns:
            doc["warnings"] = [str(w) for w in warns]
        # per-query stats (series matched, blocks read, bytes decoded,
        # cache hit/miss, decode rungs, stage timings) ride the envelope
        stats = getattr(engine, "last_stats", None)
        if stats is not None:
            doc["stats"] = stats.to_dict()
        # ?explain= : the resolved plan tree (with per-stage timings,
        # dispatch rungs and per-node legs under analyze) rides along
        if explain_doc is not None:
            doc["explain"] = explain_doc
        return json.dumps(doc).encode()

    def _time_range(self, q):
        ns = self.db.namespaces[self.namespace]
        start = _parse_time(q["start"][0]) if "start" in q else 0
        end = _parse_time(q["end"][0]) if "end" in q else (1 << 62)
        return ns, start, end

    def _labels(self, q):
        ns, start, end = self._time_range(q)
        names = [n.decode() for n in ns.index.aggregate_field_names(start, end)]
        return 200, "application/json", json.dumps(
            {"status": "success", "data": names}
        ).encode()

    def _label_values(self, name, q):
        ns, start, end = self._time_range(q)
        vals = [
            v.decode()
            for v in ns.index.aggregate_field_values(name.encode(), start, end)
        ]
        return 200, "application/json", json.dumps(
            {"status": "success", "data": vals}
        ).encode()

    def _series(self, q):
        ns, start, end = self._time_range(q)
        out = []
        for sel in q.get("match[]", []):
            matchers = _parse_series_selector(sel)
            for doc in ns.query_ids(matchers_to_query(matchers), start, end):
                out.append({k.decode(): v.decode() for k, v in doc.fields})
        return 200, "application/json", json.dumps(
            {"status": "success", "data": out}
        ).encode()

    # -- server lifecycle --

    def serve(self, host: str = "127.0.0.1", port: int = 7201) -> int:
        # arm percentile-based slow-query admission: the bar follows the
        # live p99 of THIS coordinator's request-latency histogram (with
        # M3_TPU_SLOW_QUERY_MS as floor, and as the sole bar until the
        # histogram holds enough samples to trust)
        from m3_tpu.utils import querystats
        from m3_tpu.utils.instrument import default_registry

        reg = default_registry()
        # .get, not [..]: the defaultdict must not grow outside its lock
        self._adaptive_source = \
            lambda: reg.histograms.get(("coordinator.request_seconds", ()))
        querystats.set_adaptive_source(self._adaptive_source)
        api = self

        class Handler(BaseHTTPRequestHandler):
            def _do(self, method):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if method == "POST" and self.headers.get(
                    "Content-Type", ""
                ).startswith("application/x-www-form-urlencoded"):
                    try:
                        q = {**parse_qs(body.decode()), **q}
                    except UnicodeDecodeError:
                        pass  # mislabeled binary body; routes read it raw
                status, ctype, payload, headers = api.handle(
                    method, u.path, q, body, headers=self.headers)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._do("GET")

            def do_POST(self):  # noqa: N802
                self._do("POST")

            def do_DELETE(self):  # noqa: N802
                self._do("DELETE")

            def do_PUT(self):  # noqa: N802
                self._do("PUT")

            def log_message(self, *a):  # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        thread.start()
        return self._server.server_address[1]

    def shutdown(self):
        from m3_tpu.utils import querystats

        # identity-scoped: only disarm the bar if WE registered it — a
        # sibling CoordinatorAPI's registration must survive our shutdown
        src = getattr(self, "_adaptive_source", None)
        if src is not None:
            querystats.clear_adaptive_source(src)
        if self._server:
            self._server.shutdown()
            self._server = None
