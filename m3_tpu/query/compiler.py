"""Whole-query compilation: fuse a resolved PromQL plan into ONE XLA
program per plan shape (ROADMAP #2).

The interpreter (`Engine._eval`) walks the expression tree op by op —
decode, range function, aggregation and binary ops each pay their own
dispatch ladder and materialize a host-side intermediate between stages.
Following PAPERS.md "Automatic Full Compilation of Julia Programs and ML
Models to Cloud TPUs" (compile the whole program, not the ops), this
module lowers a covered plan — selector → range function → by/without
aggregation → scalar binary ops — into a single traced/jit'd program
composed from the SAME pure stage kernels the per-op device path uses
(`ops/temporal.stage_*`, `ops/windowed_agg.stage_grouped_*`), so decoded
columns stay on device across stages and the XLA/native/scalar dispatch
decision moves from per-op to per-plan.

Covered plan shapes (the high-traffic core; everything else falls back
to the interpreter, counted, never an error):

  base:   vector selector (instant lookback gather), or
          rate/increase/delta/irate/idelta(sel[range]), or
          avg/sum/count/present_over_time(sel[range])
  over:   any chain of sum/avg/min/max/count/quantile `by`/`without`
          aggregations (at most one) and scalar-literal binary
          arithmetic (+ - * / % ^), in any order

Plan-shape cache: compiled programs are cached per plan SIGNATURE (the
op sequence) by an ``functools.lru_cache`` factory — the m3lint-blessed
keyed-cache idiom, so ``jax.jit`` is constructed once per signature, not
per call — and jax's own executable cache buckets the (series count,
step count, group count) axes, which the host prep pads to half-octave
buckets (`dispatch.next_bucket`: the smallest of {2^k, 3*2^(k-1)} that
fits). Recompiles are therefore bounded by
O(signatures x log S x log T x log G). An explicit bounded LRU
(`_PLAN_CACHE`) tracks every (signature, bucket) key served; hit/miss is
the jit tracker's executable-cache ground truth (not LRU membership) and
feeds the per-plan-shape counters and the `?explain=analyze` surface.

Numeric parity: stage math is shared with the per-op kernels and mirrors
the interpreter formula-for-formula; results are element-identical up to
XLA reassociation (prefix sums, segment-sum accumulation order — last-ulp
differences) and the documented extrapolation-threshold knife edge in
``stage_extrapolated_rate``. The seeded property sweep in
tests/test_query_compile.py enforces this envelope.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from m3_tpu.query.promql import (
    AggregateExpr,
    BinaryExpr,
    Call,
    Expr,
    MatrixSelector,
    NumberLiteral,
    VectorSelector,
)
from m3_tpu.utils import dispatch

# range-function bases: name -> (is_counter, is_rate)
_EXTRAP = {"rate": (True, True), "increase": (True, False),
           "delta": (False, False)}
_INSTANT = {"irate": (True, True), "idelta": (False, False)}
_OVER_TIME = {"avg_over_time": "avg", "sum_over_time": "sum",
              "count_over_time": "count", "present_over_time": "present"}
_AGG_OPS = {"sum", "avg", "min", "max", "count", "quantile"}
_BIN_OPS = {"+", "-", "*", "/", "%", "^"}

# bound on distinct (signature, bucket) keys tracked; jit programs are
# cached per signature below (the buckets share one traced callable)
_PLAN_CACHE_CAP = 128
_PROGRAM_CACHE_CAP = 64


@dataclass
class PlanSpec:
    """A matched, compilable plan."""

    selector: VectorSelector
    range_ns: int                 # 0 for an instant-selector base
    base: str                     # "instant" | range-function name
    stages: tuple                 # inner->outer ("bin", op, swapped, value)
    #                             # | ("agg", op, grouping, without, phi)
    nodes: tuple                  # AST nodes outer->inner for EXPLAIN

    @property
    def sig(self) -> tuple:
        """Program signature: exactly what changes the traced callable
        (ops + sides), never the data (scalars, phi, grouping labels)."""
        return (self.base, tuple(
            (st[0], st[1], st[2]) if st[0] == "bin" else (st[0], st[1])
            for st in self.stages))

    @property
    def sig_str(self) -> str:
        parts = [self.base]
        for st in self.stages:
            if st[0] == "bin":
                parts.append(f"bin:{st[1]}:{'r' if st[2] else 'l'}")
            else:
                parts.append(f"agg:{st[1]}")
        return "|".join(parts)


def _scalar_literal(e: Expr) -> float | None:
    """The float of a (possibly sign-wrapped) number literal, else None —
    the parser spells -1.5 as UnaryExpr('-', NumberLiteral(1.5))."""
    from m3_tpu.query.promql import UnaryExpr

    if isinstance(e, NumberLiteral):
        return float(e.value)
    if isinstance(e, UnaryExpr) and isinstance(e.expr, NumberLiteral):
        v = float(e.expr.value)
        return -v if e.op == "-" else v
    return None


def match(expr: Expr) -> PlanSpec | None:
    """PlanSpec when the expression is a covered chain, else None."""
    outer = []   # outer->inner stage list
    nodes = []
    e = expr
    while True:
        if isinstance(e, BinaryExpr) and e.op in _BIN_OPS \
                and not e.bool_mode:
            lhs_lit = _scalar_literal(e.lhs)
            rhs_lit = _scalar_literal(e.rhs)
            if lhs_lit is not None:
                swapped, scalar, inner = True, lhs_lit, e.rhs
            elif rhs_lit is not None:
                swapped, scalar, inner = False, rhs_lit, e.lhs
            else:
                return None
            outer.append(("bin", e.op, swapped, scalar))
            nodes.append(e)
            e = inner
            continue
        if isinstance(e, AggregateExpr) and e.op in _AGG_OPS:
            if any(st[0] == "agg" for st in outer):
                return None  # one aggregation per compiled chain
            phi = None
            if e.op == "quantile":
                phi = _scalar_literal(e.param)
                if phi is None:
                    return None
            elif e.param is not None:
                return None
            outer.append(("agg", e.op, tuple(e.grouping), bool(e.without),
                          phi))
            nodes.append(e)
            e = e.expr
            continue
        break
    if isinstance(e, VectorSelector):
        if getattr(e, "at_ns", None) in ("start", "end"):
            return None  # unresolved sentinel: not a compilable instant
        sel, range_ns, base = e, 0, "instant"
        nodes.append(e)
    elif isinstance(e, Call) and (
            e.func in _EXTRAP or e.func in _INSTANT or e.func in _OVER_TIME) \
            and len(e.args) == 1 and isinstance(e.args[0], MatrixSelector):
        sel = e.args[0].selector
        if getattr(sel, "at_ns", None) in ("start", "end"):
            return None
        range_ns, base = e.args[0].range_ns, e.func
        nodes.append(e)
        nodes.append(e.args[0])
    else:
        return None
    # execution order is inner->outer
    return PlanSpec(selector=sel, range_ns=range_ns, base=base,
                    stages=tuple(reversed(outer)), nodes=tuple(nodes))


# ---------------------------------------------------------------------------
# program factory (the per-plan jit dispatcher)
# ---------------------------------------------------------------------------


def _apply_scalar_op(op: str, a, b):
    """jnp twin of engine._apply_op restricted to arithmetic."""
    import jax.numpy as jnp

    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.fmod(a, b)
    if op == "^":
        return jnp.power(a, b)
    raise ValueError(f"unknown scalar op {op}")


@functools.lru_cache(maxsize=_PROGRAM_CACHE_CAP)
def _program(sig: tuple):
    """ONE jit'd whole-plan callable per signature (the blessed lru_cache
    factory idiom — see tools/m3lint rules_jax): shape buckets reuse it
    through jax's own executable cache."""
    import jax
    import jax.numpy as jnp

    from m3_tpu.ops import temporal, windowed_agg

    base, stages = sig

    def run(v, adj, t, csum, lo, hi, eval_ts, range_ns, seg,
            phi, scalars, num_groups: int):
        if base == "instant":
            cur = temporal.stage_instant_values(v, lo, hi)
        elif base in _EXTRAP:
            is_counter, is_rate = _EXTRAP[base]
            cur = temporal.stage_extrapolated_rate(
                v, adj, t, lo, hi, eval_ts, range_ns, is_counter, is_rate)
        elif base in _INSTANT:
            is_counter, is_rate = _INSTANT[base]
            cur = temporal.stage_instant_delta(v, t, lo, hi, is_counter,
                                               is_rate)
        else:
            cur = temporal.stage_over_time(_OVER_TIME[base], csum, lo, hi)
        si = 0
        for st in stages:
            if st[0] == "bin":
                _, op, swapped = st
                c = scalars[si]
                si += 1
                a, b = (c, cur) if swapped else (cur, c)
                nxt = _apply_scalar_op(op, a, b)
                if op == "^":
                    # the interpreter _compacts (drops all-NaN rows)
                    # between stages, and ^ is the one covered op whose
                    # elementwise math can resurrect a dead row
                    # (NaN ** 0 == 1 ** NaN == 1.0): a row dead before
                    # the stage must stay dead, so the final _compact
                    # drops exactly the rows the interpreter dropped
                    dead = jnp.all(jnp.isnan(cur), axis=1, keepdims=True)
                    nxt = jnp.where(dead, jnp.nan, nxt)
                cur = nxt
            else:
                _, op = st
                if op == "quantile":
                    cur = windowed_agg.stage_grouped_quantile(
                        cur, seg, num_groups, phi)
                else:
                    cur = windowed_agg.stage_grouped_reduce(
                        op, cur, seg, num_groups)
        return cur

    return jax.jit(run, static_argnames=("num_groups",))


# ---------------------------------------------------------------------------
# plan-shape cache bookkeeping (telemetry + boundedness)
# ---------------------------------------------------------------------------

_plan_lock = threading.Lock()
_plan_cache: OrderedDict = OrderedDict()  # key -> {"hits": n, "misses": n}

# metric-label guard: registry counters persist forever, so the shape=
# label set must be bounded even though the signature space is user-
# controlled (ever-longer scalar chains mint fresh signatures — the PR 7
# tenant-label cardinality class). First N distinct shapes get their own
# label; the tail shares "other". ?explain= still carries the full key.
_SHAPE_LABEL_CAP = 64
_shape_labels_seen: set = set()


def _shape_label(key_str: str) -> str:
    with _plan_lock:
        if key_str in _shape_labels_seen:
            return key_str
        if len(_shape_labels_seen) < _SHAPE_LABEL_CAP:
            _shape_labels_seen.add(key_str)
            return key_str
        return "other"


def _plan_cache_record(key: tuple, miss: bool) -> None:
    """Record one use of a plan-shape key. ``miss`` is the GROUND-TRUTH
    compile outcome from the jit tracker (did the executable cache grow),
    not this LRU's own membership — so an eviction here can never relabel
    a still-compiled plan as a miss, nor a real recompile after program-
    factory eviction as a hit."""
    with _plan_lock:
        rec = _plan_cache.get(key)
        if rec is None:
            rec = _plan_cache[key] = {"hits": 0, "misses": 0}
            while len(_plan_cache) > _PLAN_CACHE_CAP:
                _plan_cache.popitem(last=False)
        else:
            _plan_cache.move_to_end(key)
        rec["misses" if miss else "hits"] += 1


def plan_cache_info() -> dict:
    """Snapshot for tests and /debug surfaces."""
    with _plan_lock:
        return {"|".join(str(p) for p in k): dict(v)
                for k, v in _plan_cache.items()}


def clear_plan_cache() -> None:
    with _plan_lock:
        _plan_cache.clear()
        _shape_labels_seen.clear()


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


def _jax_ready() -> bool:
    """Compile only when jax is importable WITHOUT risking a wedge: jax
    already imported (ingest/encode initialized it), or the operator
    explicitly forced the path (M3_TPU_QUERY_COMPILE=1 accepts the
    import). Mirrors dispatch._accelerator_present's tunnel caution."""
    if "jax" in sys.modules:
        return True
    return os.environ.get("M3_TPU_QUERY_COMPILE") == "1"


def _fallback(reason: str):
    """Counted, traced, never an error."""
    from m3_tpu.query import explain as explain_mod
    from m3_tpu.utils import trace
    from m3_tpu.utils.instrument import default_registry

    dispatch.counters["query.compile[fallback]"] += 1
    default_registry().root_scope("compute").subscope(
        "query_plan").counter("fallback")
    with trace.span(trace.QUERY_COMPILE_FALLBACK, reason=reason):
        pass
    col = explain_mod.current()
    if col is not None:
        col.set_compiled({"ran": False, "reason": reason})
    return None


def _host_prefers_interpreter(spec: PlanSpec) -> bool:
    """The per-PLAN rung of the XLA/native/scalar dispatch ladder: on a
    CPU-only backend, extrapolated-rate bases are served faster by the
    interpreter's native columnar kernel (ops.native_hostops.rate_csr —
    a pointer-walk the XLA lowering can't match on host; measured ~2.4x
    in bench #9's development), so a config-enabled engine declines them
    unless an accelerator is live. M3_TPU_QUERY_COMPILE=1 (the explicit
    hatch) overrides — tests and accelerator-bound benches force the
    fused program."""
    if spec.base not in _EXTRAP:
        return False
    if dispatch._accelerator_present():
        return False
    if os.environ.get("M3_TPU_NATIVE_OPS") == "0":
        return False
    from m3_tpu.ops import native_hostops

    return native_hostops.available()


def _group_ids(labels: list, grouping: tuple, without: bool):
    """(seg ids [S], output group labels) built from the engine's shared
    ``grouping_keys`` helper — ONE definition of the by/without key
    semantics, so the compiled path cannot drift from _eval_aggregate."""
    from m3_tpu.query.engine import grouping_keys

    keys, out_labels_for = grouping_keys(labels, grouping, without)
    uniq = sorted(set(keys))
    gid = {k: i for i, k in enumerate(uniq)}
    seg = np.array([gid[k] for k in keys], np.int32) if keys \
        else np.empty(0, np.int32)
    return seg, [dict(out_labels_for[k]) for k in uniq]


def try_execute(engine, expr: Expr, eval_ts: np.ndarray):
    """Compile-and-run `expr` when covered; None means "interpreter's
    turn" (uncovered shape or jax unavailable), with the fallback counted.

    The decision is made BEFORE any storage work, so falling back never
    double-fetches or double-accounts query limits; past this point the
    compiled path either returns a result or raises like the interpreter
    would (storage errors, limits)."""
    spec = match(expr)
    if spec is None:
        return _fallback("uncovered_plan_shape")
    if not _jax_ready():
        return _fallback("jax_not_initialized")
    if os.environ.get("M3_TPU_QUERY_COMPILE") != "1" \
            and _host_prefers_interpreter(spec):
        return _fallback("host_native_faster")
    dispatch.counters["query.compile[compiled]"] += 1
    from m3_tpu.query import explain as explain_mod

    col = explain_mod.current()
    with contextlib.ExitStack() as stack:
        if col is not None:
            for node in spec.nodes[:-1]:
                stack.enter_context(col.node(node))
        # innermost node wraps the fetch: selector-stage attribution
        # lands exactly where the interpreter's plan tree puts it
        with col.node(spec.nodes[-1]) if col is not None \
                else contextlib.nullcontext():
            labels, raws = engine._fetch(spec.selector, eval_ts,
                                         spec.range_ns)
        out = _execute(engine, spec, labels, raws, eval_ts, col)
    return out


def _pad_bounds(lo: np.ndarray, hi: np.ndarray, n_samples: int):
    """Half-octave (next_bucket) padding of the [S, T] bound matrices:
    the fused program pays for every padded cell, so the compiler uses
    finer buckets than the per-op kernels' powers of two. Bounds are
    global CSR sample indices in [0, n_samples]; they ship as int32 when
    that fits — on the hot [S, T] axes that halves both the host->device
    bytes and the gather-index reads — and int64 on a >2^31-sample fetch
    (int32 would wrap negative and gather garbage silently)."""
    S, T = lo.shape
    Sp, Tp = dispatch.next_bucket(S), dispatch.next_bucket(T)
    dt = np.int32 if n_samples < 2**31 else np.int64
    lo_p = np.zeros((Sp, Tp), dt)
    hi_p = np.zeros((Sp, Tp), dt)
    lo_p[:S, :T] = lo
    hi_p[:S, :T] = hi
    return lo_p, hi_p


def _pad_eval_ts(eval_ts: np.ndarray) -> np.ndarray:
    T = len(eval_ts)
    Tp = dispatch.next_bucket(T)
    if Tp == T:
        return eval_ts
    fill = eval_ts[-1] if T else 0
    return np.concatenate([eval_ts, np.full(Tp - T, fill, np.int64)])


def _execute(engine, spec: PlanSpec, labels, raws, eval_ts, col):
    from m3_tpu.ops import temporal
    from m3_tpu.query import windows
    from m3_tpu.query.engine import Vector, _compact
    from m3_tpu.utils.instrument import default_registry

    T = len(eval_ts)
    S = raws.n_series
    agg = next((st for st in spec.stages if st[0] == "agg"), None)
    if S == 0:
        # interpreter parity: an empty fetch compacts to an empty vector
        # at the base stage, and every covered stage preserves emptiness
        vec = Vector([], np.zeros((0, T)))
        if col is not None:
            col.set_compiled({"ran": True, "cache_key": "empty",
                              "cache": "hit"})
        return vec

    shifted = engine._resolve_ts(spec.selector, eval_ts)
    bounds_range = spec.range_ns if spec.base != "instant" \
        else engine.lookback_ns
    lo, hi = raws.window_bounds_batch(shifted, bounds_range)

    # Host prep mirrors the bounds policy: per-SAMPLE sequential passes
    # (prefix sums, counter monotonization) run as one numpy pass — the
    # exact arrays the interpreter gathers from, and numpy's cumsum is an
    # order of magnitude faster than XLA:CPU's — while every per-(series,
    # step) stage fuses into the one traced program below.
    n = len(raws.values)
    v_pad, t_pad = temporal._pad_samples(raws.values, raws.times)
    if spec.base in _EXTRAP and _EXTRAP[spec.base][0]:
        adj = windows._reset_adjusted(raws)
        adj_pad = np.concatenate([adj, np.zeros(len(v_pad) - n)])
    else:  # unused by the program
        adj_pad = v_pad
    if spec.base in ("sum_over_time", "avg_over_time"):
        csum = np.empty(len(v_pad) + 1)
        csum[0] = 0.0
        np.cumsum(raws.values, out=csum[1:n + 1])
        csum[n + 1:] = csum[n]
    else:
        # unused by the traced program (count/present_over_time gather
        # only window counts; the other bases never touch csum — the
        # base is a trace-time constant) — ship one element, not
        # O(samples) zeros, on the hot path
        csum = np.zeros(1)
    lo_p, hi_p = _pad_bounds(lo, hi, n)
    eval_pad = _pad_eval_ts(shifted)
    Sp, Tp = lo_p.shape

    if agg is not None:
        _, _aop, grouping, without, phi = agg
        seg, group_labels = _group_ids(labels, grouping, without)
        G = len(group_labels)
        Gp = dispatch.next_bucket(G + 1)  # +1 reserves the pad-row group
        seg_pad = np.full(Sp, Gp - 1, np.int32)
        seg_pad[:S] = seg
    else:
        phi = None
        G, Gp = 0, 1
        seg_pad = np.zeros(Sp, np.int32)
    scalars = np.array([st[3] for st in spec.stages if st[0] == "bin"],
                       np.float64)

    sig = spec.sig
    key = (spec.sig_str, Sp, Tp, Gp)
    key_str = f"{spec.sig_str}|S{Sp}|T{Tp}|G{Gp}"
    program = _program(sig)
    t0 = time.perf_counter()
    tracker = dispatch.jit_tracker("query_plan", program)
    with tracker:
        out = program(v_pad, adj_pad, t_pad, csum, lo_p, hi_p,
                      eval_pad, np.int64(spec.range_ns), seg_pad,
                      np.float64(phi if phi is not None else 0.0),
                      scalars, num_groups=Gp)
    hit = not tracker.miss
    _plan_cache_record(key, miss=tracker.miss)
    sc = default_registry().root_scope("compute").subscope(
        "plan_cache", shape=_shape_label(key_str))
    sc.counter("hit" if hit else "miss")
    if not hit:
        # trace+lower+compile dominates the first call of a new shape
        default_registry().root_scope("compute").subscope(
            "query_plan").observe("plan_compile_seconds",
                                  time.perf_counter() - t0)
    out = np.asarray(out)

    if agg is not None:
        mat = out[:G, :T]
        out_labels = group_labels
    else:
        mat = out[:S, :T]
        drops_name = spec.base != "instant" or any(
            st[0] == "bin" for st in spec.stages)
        if drops_name:
            out_labels = [{k: v for k, v in lb.items() if k != b"__name__"}
                          for lb in labels]
        else:
            out_labels = [dict(lb) for lb in labels]
    if col is not None:
        col.set_compiled({"ran": True, "cache_key": key_str,
                          "cache": "hit" if hit else "miss"})
    return _compact(Vector(out_labels, mat))
