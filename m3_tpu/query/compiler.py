"""Whole-query compilation: fuse a resolved PromQL plan into ONE XLA
program per plan shape (ROADMAP #2).

The interpreter (`Engine._eval`) walks the expression tree op by op —
decode, range function, aggregation and binary ops each pay their own
dispatch ladder and materialize a host-side intermediate between stages.
Following PAPERS.md "Automatic Full Compilation of Julia Programs and ML
Models to Cloud TPUs" (compile the whole program, not the ops), this
module lowers a covered plan — selector → range function → by/without
aggregation → scalar binary ops — into a single traced/jit'd program
composed from the SAME pure stage kernels the per-op device path uses
(`ops/temporal.stage_*`, `ops/windowed_agg.stage_grouped_*`), so decoded
columns stay on device across stages and the XLA/native/scalar dispatch
decision moves from per-op to per-plan.

Covered plan shapes (the high-traffic core; everything else falls back
to the interpreter, counted, never an error):

  base:   vector selector (instant lookback gather), or
          rate/increase/delta/irate/idelta(sel[range]), or
          avg/sum/count/present_over_time(sel[range]), or
          min/max_over_time(sel[range]) (sparse-table range-min stage)
  over:   any chain of sum/avg/min/max/count/quantile `by`/`without`
          aggregations (at most one) and scalar-literal binary
          arithmetic (+ - * / % ^), in any order
  binop:  a TOP-LEVEL vector-vector arithmetic op between two covered
          chains under default one-to-one matching (`match_vecbin`):
          both sides run as their own fused programs and the combine is
          the interpreter's exact numpy one-to-one match — same keys,
          same duplicate-series errors, same result labels.
          on()/ignoring()/group modifiers, bool mode and comparisons
          stay with the interpreter (counted fallback).

Sharded compute plane (PR 12, ROADMAP #1): when a ``("series",)``
compute mesh is active (`parallel.mesh.active_compute_mesh` —
M3_TPU_QUERY_SHARD or a live multi-device accelerator), the SAME plan
runs across every device: host prep slices the CSR sample arrays into
per-device SLABS (each device owns a contiguous block of series rows
and only its own samples — gathers stay device-local instead of
thrashing a replicated sample array), the base stage runs under an
inner shard_map over those slabs, and every later stage boundary emits
``jax.lax.with_sharding_constraint`` (series-sharded [S, T] until the
aggregation, replicated [G, T] after it) so XLA's SPMD partitioner
lowers the grouped segment reductions to psums over the series axis
itself. The series axis pads to a multiple of the mesh size
(``dispatch.next_bucket(S, multiple=n_devices)``); numerics are
device-count independent up to float reassociation in the cross-device
reductions (exact NaN masks, 1e-9 relative — the same envelope as
single-device XLA, enforced at 1 and 8 devices by tests/test_parallel).

Plan-shape cache: compiled programs are cached per plan SIGNATURE (the
op sequence) by an ``functools.lru_cache`` factory — the m3lint-blessed
keyed-cache idiom, so ``jax.jit`` is constructed once per signature, not
per call — and jax's own executable cache buckets the (series count,
step count, group count) axes, which the host prep pads to half-octave
buckets (`dispatch.next_bucket`: the smallest of {2^k, 3*2^(k-1)} that
fits). Recompiles are therefore bounded by
O(signatures x log S x log T x log G). An explicit bounded LRU
(`_PLAN_CACHE`) tracks every (signature, bucket) key served; hit/miss is
the jit tracker's executable-cache ground truth (not LRU membership) and
feeds the per-plan-shape counters and the `?explain=analyze` surface.

Numeric parity: stage math is shared with the per-op kernels and mirrors
the interpreter formula-for-formula; results are element-identical up to
XLA reassociation (prefix sums, segment-sum accumulation order — last-ulp
differences) and the documented extrapolation-threshold knife edge in
``stage_extrapolated_rate``. The seeded property sweep in
tests/test_query_compile.py enforces this envelope.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from m3_tpu.query.promql import (
    AggregateExpr,
    BinaryExpr,
    Call,
    Expr,
    MatrixSelector,
    NumberLiteral,
    VectorSelector,
)
from m3_tpu.utils import dispatch

# range-function bases: name -> (is_counter, is_rate)
_EXTRAP = {"rate": (True, True), "increase": (True, False),
           "delta": (False, False)}
_INSTANT = {"irate": (True, True), "idelta": (False, False)}
_OVER_TIME = {"avg_over_time": "avg", "sum_over_time": "sum",
              "count_over_time": "count", "present_over_time": "present"}
_MINMAX = {"min_over_time": True, "max_over_time": False}  # name -> is_min
_AGG_OPS = {"sum", "avg", "min", "max", "count", "quantile"}
_BIN_OPS = {"+", "-", "*", "/", "%", "^"}

# bound on distinct (signature, bucket) keys tracked; jit programs are
# cached per signature below (the buckets share one traced callable)
_PLAN_CACHE_CAP = 128
_PROGRAM_CACHE_CAP = 64


@dataclass
class PlanSpec:
    """A matched, compilable plan."""

    selector: VectorSelector
    range_ns: int                 # 0 for an instant-selector base
    base: str                     # "instant" | range-function name
    stages: tuple                 # inner->outer ("bin", op, swapped, value)
    #                             # | ("agg", op, grouping, without, phi)
    nodes: tuple                  # AST nodes outer->inner for EXPLAIN

    @property
    def sig(self) -> tuple:
        """Program signature: exactly what changes the traced callable
        (ops + sides), never the data (scalars, phi, grouping labels)."""
        return (self.base, tuple(
            (st[0], st[1], st[2]) if st[0] == "bin" else (st[0], st[1])
            for st in self.stages))

    @property
    def sig_str(self) -> str:
        parts = [self.base]
        for st in self.stages:
            if st[0] == "bin":
                parts.append(f"bin:{st[1]}:{'r' if st[2] else 'l'}")
            else:
                parts.append(f"agg:{st[1]}")
        return "|".join(parts)


@dataclass
class VecBinSpec:
    """A covered vector-vector binary op: both sides are covered chains,
    matched one-to-one on their full label sets (default matching). The
    sides compile into their own fused programs; the element-wise
    combine is the interpreter's exact numpy op over the matched rows,
    so parity composes from the sides' parity."""

    op: str
    lhs: PlanSpec
    rhs: PlanSpec


def match_vecbin(expr: Expr) -> VecBinSpec | None:
    """VecBinSpec when `expr` is a top-level arithmetic binop between
    two covered chains under DEFAULT one-to-one matching, else None.
    on()/ignoring()/group modifiers, bool mode and comparisons keep the
    interpreter's richer matching machinery (counted fallback)."""
    if not isinstance(expr, BinaryExpr) or expr.op not in _BIN_OPS \
            or expr.bool_mode:
        return None
    m = expr.matching
    if m is not None and (m.on or m.labels or m.group_left
                          or m.group_right or m.include):
        return None
    if _scalar_literal(expr.lhs) is not None \
            or _scalar_literal(expr.rhs) is not None:
        return None  # scalar arithmetic is covered in-chain by match()
    lhs = match(expr.lhs)
    if lhs is None:
        return None
    rhs = match(expr.rhs)
    if rhs is None:
        return None
    return VecBinSpec(expr.op, lhs, rhs)


def _scalar_literal(e: Expr) -> float | None:
    """The float of a (possibly sign-wrapped) number literal, else None —
    the parser spells -1.5 as UnaryExpr('-', NumberLiteral(1.5))."""
    from m3_tpu.query.promql import UnaryExpr

    if isinstance(e, NumberLiteral):
        return float(e.value)
    if isinstance(e, UnaryExpr) and isinstance(e.expr, NumberLiteral):
        v = float(e.expr.value)
        return -v if e.op == "-" else v
    return None


def match(expr: Expr) -> PlanSpec | None:
    """PlanSpec when the expression is a covered chain, else None."""
    outer = []   # outer->inner stage list
    nodes = []
    e = expr
    while True:
        if isinstance(e, BinaryExpr) and e.op in _BIN_OPS \
                and not e.bool_mode:
            lhs_lit = _scalar_literal(e.lhs)
            rhs_lit = _scalar_literal(e.rhs)
            if lhs_lit is not None:
                swapped, scalar, inner = True, lhs_lit, e.rhs
            elif rhs_lit is not None:
                swapped, scalar, inner = False, rhs_lit, e.lhs
            else:
                return None
            outer.append(("bin", e.op, swapped, scalar))
            nodes.append(e)
            e = inner
            continue
        if isinstance(e, AggregateExpr) and e.op in _AGG_OPS:
            if any(st[0] == "agg" for st in outer):
                return None  # one aggregation per compiled chain
            phi = None
            if e.op == "quantile":
                phi = _scalar_literal(e.param)
                if phi is None:
                    return None
            elif e.param is not None:
                return None
            outer.append(("agg", e.op, tuple(e.grouping), bool(e.without),
                          phi))
            nodes.append(e)
            e = e.expr
            continue
        break
    if isinstance(e, VectorSelector):
        if getattr(e, "at_ns", None) in ("start", "end"):
            return None  # unresolved sentinel: not a compilable instant
        sel, range_ns, base = e, 0, "instant"
        nodes.append(e)
    elif isinstance(e, Call) and (
            e.func in _EXTRAP or e.func in _INSTANT
            or e.func in _OVER_TIME or e.func in _MINMAX) \
            and len(e.args) == 1 and isinstance(e.args[0], MatrixSelector):
        sel = e.args[0].selector
        if getattr(sel, "at_ns", None) in ("start", "end"):
            return None
        range_ns, base = e.args[0].range_ns, e.func
        nodes.append(e)
        nodes.append(e.args[0])
    else:
        return None
    # execution order is inner->outer
    return PlanSpec(selector=sel, range_ns=range_ns, base=base,
                    stages=tuple(reversed(outer)), nodes=tuple(nodes))


# ---------------------------------------------------------------------------
# program factory (the per-plan jit dispatcher)
# ---------------------------------------------------------------------------


def _apply_scalar_op(op: str, a, b):
    """jnp twin of engine._apply_op restricted to arithmetic."""
    import jax.numpy as jnp

    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.fmod(a, b)
    if op == "^":
        return jnp.power(a, b)
    raise ValueError(f"unknown scalar op {op}")


@functools.lru_cache(maxsize=_PROGRAM_CACHE_CAP)
def _program(sig: tuple, mesh=None):
    """ONE jit'd whole-plan callable per (signature, mesh) — the blessed
    lru_cache factory idiom (see tools/m3lint rules_jax): shape buckets
    reuse it through jax's own executable cache, and the cached
    ``compute_mesh`` singletons make the mesh key identity-stable.

    Sample inputs arrive as [n_dev, cap] SLABS (n_dev == 1 without a
    mesh): device d owns rows [d*Sp/n, (d+1)*Sp/n) and exactly those
    rows' samples, with lo/hi rebased slab-local by host prep. On a mesh
    the base stage runs under shard_map (every gather device-local) and
    each later stage boundary emits with_sharding_constraint — series-
    sharded until the aggregation stage, replicated after it — so the
    SPMD partitioner lowers the grouped segment reductions to psums over
    the series axis."""
    import jax
    import jax.numpy as jnp

    from m3_tpu.ops import temporal, windowed_agg

    base, stages = sig
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # jax<0.5: experimental spelling
            from jax.experimental.shard_map import shard_map
        from m3_tpu.parallel.mesh import replicated_sharding, row_sharding

        row_sh = row_sharding(mesh)
        rep_sh = replicated_sharding(mesh)

    def _constrain(x, grouped: bool):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, rep_sh if grouped else row_sh)

    def _base_stage(v, adj, t, csum, bmat, lo, hi, eval_ts, range_ns,
                    mm_levels: int):
        """The slab-local base stage: pure stage-kernel math over ONE
        device's samples (or the whole array when unsharded)."""
        if base == "instant":
            return temporal.stage_instant_values(v, lo, hi)
        if base in _EXTRAP:
            is_counter, is_rate = _EXTRAP[base]
            return temporal.stage_extrapolated_rate(
                v, adj, t, lo, hi, eval_ts, range_ns, is_counter, is_rate)
        if base in _INSTANT:
            is_counter, is_rate = _INSTANT[base]
            return temporal.stage_instant_delta(v, t, lo, hi, is_counter,
                                                is_rate)
        if base in _MINMAX:
            if mm_levels == 0:
                # sparse table would exceed the scratch cap: host prep
                # computed the base matrix with the interpreter's exact
                # reduceat math and ships it through bmat
                return bmat
            return temporal.stage_window_minmax(v, lo, hi, mm_levels,
                                                _MINMAX[base])
        return temporal.stage_over_time(_OVER_TIME[base], csum, lo, hi)

    def run(vs, adjs, ts, csums, bmat, lo, hi, eval_ts, range_ns, seg,
            phi, scalars, num_groups: int, mm_levels: int):
        if mesh is None:
            cur = _base_stage(vs[0], adjs[0], ts[0], csums[0], bmat,
                              lo, hi, eval_ts, range_ns, mm_levels)
        elif base in _MINMAX and mm_levels == 0:
            cur = bmat  # host-computed base, already row-sharded
        else:
            def local(vs, adjs, ts, csums, lo, hi, eval_ts, range_ns):
                return _base_stage(vs[0], adjs[0], ts[0], csums[0], None,
                                   lo, hi, eval_ts, range_ns, mm_levels)

            cur = shard_map(
                local, mesh=mesh,
                in_specs=(P("series", None),) * 6 + (P(None), P()),
                out_specs=P("series", None),
            )(vs, adjs, ts, csums, lo, hi, eval_ts, range_ns)
        cur = _constrain(cur, grouped=False)
        si = 0
        grouped = False
        for st in stages:
            if st[0] == "bin":
                _, op, swapped = st
                c = scalars[si]
                si += 1
                a, b = (c, cur) if swapped else (cur, c)
                nxt = _apply_scalar_op(op, a, b)
                if op == "^":
                    # the interpreter _compacts (drops all-NaN rows)
                    # between stages, and ^ is the one covered op whose
                    # elementwise math can resurrect a dead row
                    # (NaN ** 0 == 1 ** NaN == 1.0): a row dead before
                    # the stage must stay dead, so the final _compact
                    # drops exactly the rows the interpreter dropped
                    dead = jnp.all(jnp.isnan(cur), axis=1, keepdims=True)
                    nxt = jnp.where(dead, jnp.nan, nxt)
                cur = nxt
            else:
                _, op = st
                if op == "quantile":
                    cur = windowed_agg.stage_grouped_quantile(
                        cur, seg, num_groups, phi)
                else:
                    cur = windowed_agg.stage_grouped_reduce(
                        op, cur, seg, num_groups)
                grouped = True
            cur = _constrain(cur, grouped)
        return cur

    return jax.jit(run, static_argnames=("num_groups", "mm_levels"))


# ---------------------------------------------------------------------------
# plan-shape cache bookkeeping (telemetry + boundedness)
# ---------------------------------------------------------------------------

_plan_lock = threading.Lock()
_plan_cache: OrderedDict = OrderedDict()  # key -> {"hits": n, "misses": n}
_plan_cache_evictions = 0

# metric-label guard: registry counters persist forever, so the shape=
# label set must be bounded even though the signature space is user-
# controlled (ever-longer scalar chains mint fresh signatures — the PR 7
# tenant-label cardinality class). First N distinct shapes get their own
# label; the tail shares "other". ?explain= still carries the full key.
_SHAPE_LABEL_CAP = 64
_shape_labels_seen: set = set()


def _shape_label(key_str: str) -> str:
    with _plan_lock:
        if key_str in _shape_labels_seen:
            return key_str
        if len(_shape_labels_seen) < _SHAPE_LABEL_CAP:
            _shape_labels_seen.add(key_str)
            return key_str
        return "other"


def _plan_cache_record(key: tuple, miss: bool) -> None:
    """Record one use of a plan-shape key. ``miss`` is the GROUND-TRUTH
    compile outcome from the jit tracker (did the executable cache grow),
    not this LRU's own membership — so an eviction here can never relabel
    a still-compiled plan as a miss, nor a real recompile after program-
    factory eviction as a hit."""
    global _plan_cache_evictions
    with _plan_lock:
        rec = _plan_cache.get(key)
        if rec is None:
            rec = _plan_cache[key] = {"hits": 0, "misses": 0}
            while len(_plan_cache) > _PLAN_CACHE_CAP:
                _plan_cache.popitem(last=False)
                _plan_cache_evictions += 1
        else:
            _plan_cache.move_to_end(key)
        rec["misses" if miss else "hits"] += 1


def plan_cache_info() -> dict:
    """Snapshot for tests and /debug surfaces."""
    with _plan_lock:
        return {"|".join(str(p) for p in k): dict(v)
                for k, v in _plan_cache.items()}


def plan_cache_stats() -> dict:
    """Occupancy summary for /debug/compute: bookkeeping entries, cap,
    LRU evictions, and cumulative hit/miss totals across live keys."""
    with _plan_lock:
        hits = sum(r["hits"] for r in _plan_cache.values())
        misses = sum(r["misses"] for r in _plan_cache.values())
        return {"entries": len(_plan_cache), "cap": _PLAN_CACHE_CAP,
                "evictions": _plan_cache_evictions,
                "hits": hits, "misses": misses}


def clear_plan_cache() -> None:
    with _plan_lock:
        _plan_cache.clear()
        _shape_labels_seen.clear()


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------


def _jax_ready() -> bool:
    """Compile only when jax is importable WITHOUT risking a wedge —
    the shared dispatch.jax_ready rung (jax already imported, or
    M3_TPU_QUERY_COMPILE=1 explicitly accepts the import)."""
    return dispatch.jax_ready("M3_TPU_QUERY_COMPILE")


def _fallback(reason: str):
    """Counted, traced, never an error."""
    from m3_tpu.query import explain as explain_mod
    from m3_tpu.utils import trace
    from m3_tpu.utils.instrument import default_registry

    dispatch.counters["query.compile[fallback]"] += 1
    default_registry().root_scope("compute").subscope(
        "query_plan").counter("fallback")
    with trace.span(trace.QUERY_COMPILE_FALLBACK, reason=reason):
        pass
    col = explain_mod.current()
    if col is not None:
        col.set_compiled({"ran": False, "reason": reason})
    return None


def _host_prefers_interpreter(spec: PlanSpec) -> bool:
    """The per-PLAN rung of the XLA/native/scalar dispatch ladder: on a
    CPU-only backend, extrapolated-rate bases are served faster by the
    interpreter's native columnar kernel (ops.native_hostops.rate_csr —
    a pointer-walk the XLA lowering can't match on host; measured ~2.4x
    in bench #9's development), so a config-enabled engine declines them
    unless an accelerator is live. M3_TPU_QUERY_COMPILE=1 (the explicit
    hatch) overrides — tests and accelerator-bound benches force the
    fused program."""
    if spec.base not in _EXTRAP:
        return False
    if dispatch._accelerator_present():
        return False
    if os.environ.get("M3_TPU_NATIVE_OPS") == "0":
        return False
    from m3_tpu.ops import native_hostops

    return native_hostops.available()


def _group_ids(labels: list, grouping: tuple, without: bool):
    """(seg ids [S], output group labels) built from the engine's shared
    ``grouping_keys`` helper — ONE definition of the by/without key
    semantics, so the compiled path cannot drift from _eval_aggregate."""
    from m3_tpu.query.engine import grouping_keys

    keys, out_labels_for = grouping_keys(labels, grouping, without)
    uniq = sorted(set(keys))
    gid = {k: i for i, k in enumerate(uniq)}
    seg = np.array([gid[k] for k in keys], np.int32) if keys \
        else np.empty(0, np.int32)
    return seg, [dict(out_labels_for[k]) for k in uniq]


def try_execute(engine, expr: Expr, eval_ts: np.ndarray):
    """Compile-and-run `expr` when covered; None means "interpreter's
    turn" (uncovered shape or jax unavailable), with the fallback counted.

    The decision is made BEFORE any storage work, so falling back never
    double-fetches or double-accounts query limits; past this point the
    compiled path either returns a result or raises like the interpreter
    would (storage errors, limits)."""
    spec = match(expr)
    if spec is None:
        vspec = match_vecbin(expr)
        if vspec is None:
            return _fallback("uncovered_plan_shape")
        return _try_execute_vecbin(engine, expr, vspec, eval_ts)
    if not _jax_ready():
        return _fallback("jax_not_initialized")
    if os.environ.get("M3_TPU_QUERY_COMPILE") != "1" \
            and _host_prefers_interpreter(spec):
        return _fallback("host_native_faster")
    dispatch.counters["query.compile[compiled]"] += 1
    from m3_tpu.query import explain as explain_mod

    col = explain_mod.current()
    return _run_plan(engine, spec, eval_ts, col)


def _run_plan(engine, spec: PlanSpec, eval_ts, col):
    """Fetch + fused execution of ONE covered chain (shared by single-
    plan queries and each side of a compiled vector-vector binop)."""
    with contextlib.ExitStack() as stack:
        if col is not None:
            for node in spec.nodes[:-1]:
                stack.enter_context(col.node(node))
        # innermost node wraps the fetch: selector-stage attribution
        # lands exactly where the interpreter's plan tree puts it
        with col.node(spec.nodes[-1]) if col is not None \
                else contextlib.nullcontext():
            labels, raws = engine._fetch(spec.selector, eval_ts,
                                         spec.range_ns)
        out = _execute(engine, spec, labels, raws, eval_ts, col)
    return out


def _try_execute_vecbin(engine, expr, vspec: VecBinSpec, eval_ts):
    """Serve a covered vector-vector binop: each side runs as its own
    fused program (two fetches, exactly like the interpreter's two
    subtree evaluations), then the interpreter's one-to-one default
    matching combines them element-wise in numpy — identical match-key,
    duplicate-series and result-label semantics, including the
    EvalErrors the interpreter raises for many-to-many/many-to-one."""
    if not _jax_ready():
        return _fallback("jax_not_initialized")
    if os.environ.get("M3_TPU_QUERY_COMPILE") != "1" \
            and (_host_prefers_interpreter(vspec.lhs)
                 or _host_prefers_interpreter(vspec.rhs)):
        return _fallback("host_native_faster")
    dispatch.counters["query.compile[compiled]"] += 1
    from m3_tpu.query import explain as explain_mod

    col = explain_mod.current()
    with col.node(expr) if col is not None else contextlib.nullcontext():
        lhs = _run_plan(engine, vspec.lhs, eval_ts, col)
        l_info = col.compiled if col is not None else None
        rhs = _run_plan(engine, vspec.rhs, eval_ts, col)
        r_info = col.compiled if col is not None else None
        out = _combine_vecbin(engine, vspec.op, lhs, rhs)
    if col is not None:
        col.set_compiled({"ran": True, "binop": vspec.op,
                          "sides": [l_info, r_info]})
    return out


def _combine_vecbin(engine, op: str, lhs, rhs):
    """The interpreter's `_vector_binary` restricted to the covered
    shape (arithmetic op, default matching, no group modifiers): same
    match keys, same duplicate-series errors, same result labels, same
    numpy element-wise math — so NaN masks and values are exactly what
    the interpreter computes from the same side vectors."""
    from m3_tpu.query.engine import EvalError, Vector, _apply_op, _compact

    rmap: dict[tuple, int] = {}
    for j, lb in enumerate(rhs.labels):
        k = engine._match_key(lb, None)
        if k in rmap:
            raise EvalError(
                "many-to-many vector matching: duplicate series on "
                "'one' side")
        rmap[k] = j
    out_l, out_v = [], []
    seen: dict[tuple, int] = {}
    for i, lb in enumerate(lhs.labels):
        k = engine._match_key(lb, None)
        j = rmap.get(k)
        if j is None:
            continue
        if k in seen:
            raise EvalError(
                "many-to-one matching requires group_left/group_right")
        seen[k] = i
        raw = _apply_op(op, lhs.values[i], rhs.values[j])
        out_l.append(engine._result_labels(lb, rhs.labels[j], None, False))
        out_v.append(raw)
    T = lhs.values.shape[1] if len(lhs.labels) else (
        rhs.values.shape[1] if len(rhs.labels) else 0
    )
    return _compact(Vector(out_l, np.stack(out_v) if out_v
                           else np.zeros((0, T))))


def _pad_bounds(lo: np.ndarray, hi: np.ndarray, n_samples: int, Sp: int):
    """Half-octave (next_bucket) padding of the [S, T] bound matrices:
    the fused program pays for every padded cell, so the compiler uses
    finer buckets than the per-op kernels' powers of two. ``Sp`` is the
    caller's series bucket (a multiple of the mesh size when sharded).
    Bounds are slab-local CSR sample indices in [0, n_samples]; they
    ship as int32 when that fits — on the hot [S, T] axes that halves
    both the host->device bytes and the gather-index reads — and int64
    on a >2^31-sample slab (int32 would wrap negative and gather
    garbage silently)."""
    S, T = lo.shape
    Tp = dispatch.next_bucket(T)
    dt = np.int32 if n_samples < 2**31 else np.int64
    lo_p = np.zeros((Sp, Tp), dt)
    hi_p = np.zeros((Sp, Tp), dt)
    lo_p[:S, :T] = lo
    hi_p[:S, :T] = hi
    return lo_p, hi_p


# slabs beyond this multiple of the balanced sample volume mean a
# pathologically skewed series->sample distribution; the unsharded
# program is cheaper than shipping mostly-padding slabs
_MESH_SKEW_FACTOR = 4


def _slab_cuts(offsets: np.ndarray, S: int, Sp: int, n_dev: int):
    """Per-device sample-slab boundaries: device d owns the contiguous
    row block [d*Sp/n, (d+1)*Sp/n) and — CSR rows being contiguous —
    exactly one sample slice. Returns (sample cut [n+1], per-row slab
    base offset [S]); padded rows (S..Sp) keep their zero bounds and
    never rebase.

    ``offsets`` may come straight off a binary wire frame
    (utils/wire.unpack_samples -> session CSR merge -> RaggedSeries):
    the frame codec lands int64 row offsets in exactly this layout, so
    a cluster fanout read reaches slab prep with zero per-series
    re-assembly between the HTTP socket and the device slabs."""
    offsets = np.ascontiguousarray(offsets, np.int64)
    rows_per = Sp // n_dev
    row_cut = np.minimum(np.arange(n_dev + 1) * rows_per, S)
    cut = offsets[row_cut]
    base_off = np.repeat(cut[:-1], np.diff(row_cut))
    return cut, base_off


def _fill_slabs(arr: np.ndarray, cut: np.ndarray, cap: int, fill, dtype):
    """[n_dev, cap] slab matrix from one CSR array (one slice per slab)."""
    n_dev = len(cut) - 1
    out = np.full((n_dev, cap), fill, dtype)
    for d in range(n_dev):
        a, b = int(cut[d]), int(cut[d + 1])
        out[d, :b - a] = arr[a:b]
    return out


def _pad_eval_ts(eval_ts: np.ndarray) -> np.ndarray:
    T = len(eval_ts)
    Tp = dispatch.next_bucket(T)
    if Tp == T:
        return eval_ts
    fill = eval_ts[-1] if T else 0
    return np.concatenate([eval_ts, np.full(Tp - T, fill, np.int64)])


# plan bases whose output tolerance permits the hot tier's bf16 value
# mirror (negotiated per query via hottier.negotiated_precision): bases
# that read raw values directly and whose consumers accept last-point /
# extremum precision at bf16 (~3 decimal digits). Rate/delta bases stay
# full precision — differences of close counter values amplify
# quantization — and csum-driven bases gain nothing (the program never
# reads the value slab).
_BF16_OK_BASES = {"instant", "min_over_time", "max_over_time"}


def _prepare_slabs(engine, spec: PlanSpec, labels, raws, shifted,
                   T: int, S: int, agg, precision: str) -> dict:
    """Host prep for one covered plan: window bounds, per-device slab
    fill, grouping — everything about the call that is determined by
    (fetch content, plan base, grid) and therefore cacheable in the
    device-resident hot tier.  Returns the prepared-entry dict; arrays
    are committed to device (ordinary host buffers on CPU backends) so
    a warm entry re-runs the program with zero host->device transfer."""
    from m3_tpu.ops import temporal
    from m3_tpu.parallel import mesh as mesh_mod
    from m3_tpu.query import windows
    from m3_tpu.utils.instrument import default_registry

    bounds_range = spec.range_ns if spec.base != "instant" \
        else engine.lookback_ns
    lo, hi = raws.window_bounds_batch(shifted, bounds_range)

    # Host prep mirrors the bounds policy: per-SAMPLE sequential passes
    # (prefix sums, counter monotonization) run as one numpy pass — the
    # exact arrays the interpreter gathers from, and numpy's cumsum is an
    # order of magnitude faster than XLA:CPU's — while every per-(series,
    # step) stage fuses into the one traced program below. Samples ship
    # as per-device SLABS (one slab without a mesh): each device owns a
    # contiguous block of series rows and exactly those rows' samples,
    # with lo/hi rebased slab-local, so sharded gathers never touch
    # another device's sample volume.
    n = len(raws.values)
    mesh = mesh_mod.active_compute_mesh()
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    Sp = dispatch.next_bucket(S, multiple=n_dev)
    cut, base_off = _slab_cuts(raws.offsets, S, Sp, n_dev)
    cap = dispatch.next_pow2(int(np.diff(cut).max()))
    if mesh is not None and \
            n_dev * cap > _MESH_SKEW_FACTOR * dispatch.next_pow2(max(n, 1)):
        default_registry().root_scope("compute").subscope(
            "mesh", devices=str(n_dev)).counter("skew_fallback")
        mesh, n_dev = None, 1
        Sp = dispatch.next_bucket(S)
        cut, base_off = _slab_cuts(raws.offsets, S, Sp, 1)
        cap = dispatch.next_pow2(max(n, 1))

    lo_p, hi_p = _pad_bounds(lo - base_off[:, None], hi - base_off[:, None],
                             cap, Sp)
    eval_pad = _pad_eval_ts(shifted)
    Tp = lo_p.shape[1]

    dummy = np.zeros((n_dev, 1))
    ts = np.zeros((n_dev, 1), np.int64)
    mm_levels = 0
    bmat = np.zeros((1, 1))
    vs = adjs = None
    if spec.base in _MINMAX:
        max_len = int((hi - lo).max()) if lo.size else 0
        mm_levels = temporal.minmax_levels(max_len)
        if mm_levels * cap * n_dev > temporal.MINMAX_SCRATCH_ELEMS:
            # sparse table over the scratch cap: compute the base matrix
            # with the interpreter's exact host reduceat and fuse only
            # the downstream stages (mm_levels == 0 selects this in the
            # program signature's static bucket; the sample slabs stay
            # unbuilt — the program only reads bmat on this path)
            mm_levels = 0
            op = np.minimum if _MINMAX[spec.base] else np.maximum
            bmat = np.full((Sp, Tp), np.nan)
            bmat[:S, :T] = windows._reduceat(op, raws.values, lo, hi, np.nan)
    if spec.base == "instant" or spec.base in _EXTRAP \
            or spec.base in _INSTANT or mm_levels > 0:
        vs = _fill_slabs(raws.values, cut, cap, 0.0, np.float64)
    if spec.base in _EXTRAP or spec.base in _INSTANT:
        ts = _fill_slabs(raws.times, cut, cap, np.iinfo(np.int64).max,
                         np.int64)
    if spec.base in _EXTRAP and _EXTRAP[spec.base][0]:
        # counter monotonization is global host prep (bit parity with the
        # interpreter's _reset_adjusted), then sliced per slab
        adjs = _fill_slabs(windows._reset_adjusted(raws), cut, cap, 0.0,
                           np.float64)
    if spec.base in ("sum_over_time", "avg_over_time"):
        csum = np.empty(n + 1)
        csum[0] = 0.0
        np.cumsum(raws.values, out=csum[1:n + 1])
        # slab csums are SLICES of the one global prefix array, so the
        # fused csums[hi]-csums[lo] gather stays bit-identical to the
        # interpreter's global gather on every device count
        csums = np.empty((n_dev, cap + 1))
        for d in range(n_dev):
            a, b = int(cut[d]), int(cut[d + 1])
            csums[d, :b - a + 1] = csum[a:b + 1]
            csums[d, b - a + 1:] = csum[b]
    else:
        # unused by the traced program for every other base (a trace-time
        # constant) — ship one element per device, not O(samples) zeros
        csums = dummy
    if vs is None:
        vs = dummy
    if adjs is None:
        adjs = vs

    if agg is not None:
        _, _aop, grouping, without, _phi = agg
        seg, group_labels = _group_ids(labels, grouping, without)
        G = len(group_labels)
        Gp = dispatch.next_bucket(G + 1)  # +1 reserves the pad-row group
        seg_pad = np.full(Sp, Gp - 1, np.int32)
        seg_pad[:S] = seg
    else:
        group_labels = None
        G, Gp = 0, 1
        seg_pad = np.zeros(Sp, np.int32)

    adjs_is_vs = adjs is vs
    import jax
    import jax.numpy as jnp

    if mesh is not None:
        row_sh = mesh_mod.row_sharding(mesh)

        def put(a):
            return jax.device_put(a, row_sh)

        seg_dev = jax.device_put(seg_pad, mesh_mod.vec_sharding(mesh))
    else:
        put = jax.device_put
        seg_dev = jax.device_put(seg_pad)
    if adjs_is_vs:
        vs = adjs = put(vs)
    else:
        vs, adjs = put(vs), put(adjs)
    if precision == "bf16":
        # the reduced-precision mirror: half the resident bytes; the
        # same quantized values serve the miss call and every warm hit,
        # so repeats are self-consistent
        vs = vs.astype(jnp.bfloat16)
        if adjs_is_vs:
            adjs = vs
    ts, csums = put(ts), put(csums)
    lo_p, hi_p = put(lo_p), put(hi_p)
    eval_pad = jax.device_put(eval_pad)
    if spec.base in _MINMAX and mm_levels == 0:
        bmat = put(bmat)
    arrays = [vs, ts, csums, lo_p, hi_p, eval_pad, seg_dev, bmat]
    if not adjs_is_vs:
        arrays.append(adjs)
    nbytes = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
    return {"mesh": mesh, "n_dev": n_dev, "Sp": Sp, "Tp": Tp, "Gp": Gp,
            "G": G, "cap": cap, "mm_levels": mm_levels,
            "group_labels": group_labels, "adjs_is_vs": adjs_is_vs,
            "vs": vs, "adjs": adjs, "ts": ts, "csums": csums,
            "bmat": bmat, "lo_p": lo_p, "hi_p": hi_p,
            "eval_pad": eval_pad, "seg_pad": seg_dev,
            "precision": precision, "nbytes": nbytes}


def _execute(engine, spec: PlanSpec, labels, raws, eval_ts, col):
    import zlib

    from m3_tpu.parallel import mesh as mesh_mod
    from m3_tpu.query.engine import Vector, _compact
    from m3_tpu.storage import hottier
    from m3_tpu.utils.instrument import default_registry

    T = len(eval_ts)
    S = raws.n_series
    agg = next((st for st in spec.stages if st[0] == "agg"), None)
    if S == 0:
        # interpreter parity: an empty fetch compacts to an empty vector
        # at the base stage, and every covered stage preserves emptiness
        vec = Vector([], np.zeros((0, T)))
        if col is not None:
            col.set_compiled({"ran": True, "cache_key": "empty",
                              "cache": "hit"})
        return vec

    shifted = engine._resolve_ts(spec.selector, eval_ts)

    # device-resident hot tier probe (ROADMAP #3): the prepared slab set
    # is fully determined by (fetch content version, base, grid,
    # grouping, precision, requested device count) — a warm entry skips
    # window bounds, slab fill AND the host->device transfer
    mesh_req = mesh_mod.active_compute_mesh()
    n_dev_req = int(mesh_req.devices.size) if mesh_req is not None else 1
    tier = hottier.default()
    precision = "f64"
    if hottier.query_precision() == "bf16" and spec.base in _BF16_OK_BASES:
        precision = "bf16"
    bounds_range = spec.range_ns if spec.base != "instant" \
        else engine.lookback_ns
    hkey = None
    entry = None
    if tier is not None and getattr(raws, "fetch_key", None) is not None:
        agg_key = (agg[2], agg[3]) if agg is not None else None
        grid_fp = (T, zlib.adler32(shifted.tobytes()))
        hkey = (raws.fetch_key, spec.base, int(bounds_range), grid_fp,
                agg_key, precision, n_dev_req)
        entry = tier.get(hkey)
    hot_state = None
    if hkey is not None:
        hot_state = "hit" if entry is not None else "miss"
        default_registry().root_scope("storage").subscope(
            "hot_tier").counter(hot_state)
    if entry is None:
        entry = _prepare_slabs(engine, spec, labels, raws, shifted, T, S,
                               agg, precision)
        if hkey is not None:
            tier.put(hkey, entry, entry["nbytes"])
            default_registry().root_scope("storage").subscope(
                "hot_tier").observe("hot_tier_entry_bytes",
                                    float(entry["nbytes"]))

    mesh = entry["mesh"]
    n_dev = entry["n_dev"]
    Sp, Tp, Gp, cap = entry["Sp"], entry["Tp"], entry["Gp"], entry["cap"]
    mm_levels = entry["mm_levels"]
    G = entry["G"]
    group_labels = entry["group_labels"]
    vs, adjs = entry["vs"], entry["adjs"]
    ts, csums, bmat = entry["ts"], entry["csums"], entry["bmat"]
    lo_p, hi_p = entry["lo_p"], entry["hi_p"]
    eval_pad, seg_pad = entry["eval_pad"], entry["seg_pad"]
    if entry["precision"] == "bf16":
        import jax.numpy as jnp

        vs = vs.astype(jnp.float64)
        if entry["adjs_is_vs"]:
            adjs = vs
    phi = agg[4] if agg is not None else None
    scalars = np.array([st[3] for st in spec.stages if st[0] == "bin"],
                       np.float64)

    sig = spec.sig
    key = (spec.sig_str, Sp, Tp, Gp) + \
        ((n_dev, cap) if mesh is not None else ())
    key_str = f"{spec.sig_str}|S{Sp}|T{Tp}|G{Gp}" + \
        (f"|M{n_dev}x{cap}" if mesh is not None else "")
    program = _program(sig, mesh)
    if mesh is not None:
        dispatch.counters["query.compile[sharded]"] += 1
        default_registry().root_scope("compute").subscope(
            "mesh", devices=str(n_dev)).counter("dispatch")
    t0 = time.perf_counter()
    prog_args = (vs, adjs, ts, csums, bmat, lo_p, hi_p,
                 eval_pad, np.int64(spec.range_ns), seg_pad,
                 np.float64(phi if phi is not None else 0.0), scalars)
    tracker = dispatch.jit_tracker(
        "query_plan", program, sig=key_str,
        lower=lambda: program.lower(*prog_args, num_groups=Gp,
                                    mm_levels=mm_levels))
    with tracker:
        out = program(*prog_args, num_groups=Gp, mm_levels=mm_levels)
    hit = not tracker.miss
    _plan_cache_record(key, miss=tracker.miss)
    sc = default_registry().root_scope("compute").subscope(
        "plan_cache", shape=_shape_label(key_str))
    sc.counter("hit" if hit else "miss")
    if not hit:
        # trace+lower+compile dominates the first call of a new shape
        default_registry().root_scope("compute").subscope(
            "query_plan").observe("plan_compile_seconds",
                                  time.perf_counter() - t0)
    out = np.asarray(out)

    if agg is not None:
        mat = out[:G, :T]
        out_labels = group_labels
    else:
        mat = out[:S, :T]
        drops_name = spec.base != "instant" or any(
            st[0] == "bin" for st in spec.stages)
        if drops_name:
            out_labels = [{k: v for k, v in lb.items() if k != b"__name__"}
                          for lb in labels]
        else:
            out_labels = [dict(lb) for lb in labels]
    # padding-waste ledger: logical vs half-octave-padded elements per
    # program axis, for THIS query's slabs (warm hot-tier entries count
    # too — the padded cells re-run every call, not just at prep)
    from m3_tpu.utils import compute_stats

    n_samples = len(raws.values)
    compute_stats.record_waste("query_slabs", "series", S, Sp)
    compute_stats.record_waste("query_slabs", "time", T, Tp)
    if agg is not None:
        compute_stats.record_waste("query_slabs", "groups", G + 1, Gp)
    compute_stats.record_waste("query_slabs", "samples", n_samples,
                               n_dev * cap)

    if col is not None:
        info = {"ran": True, "cache_key": key_str,
                "cache": "hit" if hit else "miss"}
        # the ?explain=analyze device block: what this query cost on the
        # compute plane — execute/compile wall, static FLOP/byte profile
        # (captured once per compile), padding waste, mesh width
        padding = {"series": {"logical": S, "padded": Sp},
                   "time": {"logical": T, "padded": Tp}}
        if agg is not None:
            padding["groups"] = {"logical": G + 1, "padded": Gp}
        device = {"program": "query_plan", "sig": key_str,
                  "cache": "hit" if hit else "miss",
                  ("compile_seconds" if not hit else "execute_seconds"):
                      tracker.seconds,
                  "padding": padding,
                  "waste_ratio": round(1.0 - (S * T) / (Sp * Tp), 6),
                  "mesh_devices": n_dev}
        prof = compute_stats.profile_for("query_plan", key_str)
        if prof:
            device.update(prof)
        info["device"] = device
        if hot_state is not None:
            # the ?explain=analyze hot_tier block: did warm device pages
            # serve this query's slabs, and at what precision
            info["hot_tier"] = {
                "hit": hot_state == "hit",
                "precision": entry["precision"],
                "entries": len(tier),
                "bytes": tier.bytes_used,
            }
        if mesh is not None:
            info["mesh"] = {"axis": "series", "devices": n_dev}
            stage_shardings = [{"stage": f"base:{spec.base}",
                               "spec": "P('series', None)"}]
            grouped = False
            for st in spec.stages:
                if st[0] == "agg":
                    grouped = True
                    stage_shardings.append(
                        {"stage": f"agg:{st[1]}", "spec": "P()"})
                else:
                    stage_shardings.append(
                        {"stage": f"bin:{st[1]}",
                         "spec": "P()" if grouped else "P('series', None)"})
            info["sharding"] = stage_shardings
        col.set_compiled(info)
    return _compact(Vector(out_labels, mat))
