"""PromQL evaluation engine.

Role parity with the reference executor + function library
(/root/reference/src/query/executor/engine.go:111, functions/*): parse to an
AST (promql.py), then evaluate bottom-up over columnar [series x steps]
value matrices — every operator is a whole-matrix transform (the reference
streams per-series blocks through transform nodes; here the step grid is one
tensor program, the layout the TPU path consumes directly).

Numeric semantics follow upstream Prometheus: 5m lookback staleness,
extrapolated rates, population stddev, interpolated quantiles, bucket
interpolation for histogram_quantile, vector matching with __name__ excluded.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import numpy as np

from m3_tpu.query import promql, windows
from m3_tpu.query.promql import (
    AggregateExpr,
    BinaryExpr,
    Call,
    Expr,
    MatrixSelector,
    NumberLiteral,
    StringLiteral,
    SubqueryExpr,
    UnaryExpr,
    VectorMatching,
    VectorSelector,
)
from m3_tpu.query.windows import NS, RaggedSeries

DEFAULT_LOOKBACK_NS = 5 * 60 * NS

# accounting moved to the storage layer so every read path shares the
# budget; re-exported here for the existing query-facing API
from m3_tpu.storage.limits import QueryLimitError, QueryLimits  # noqa: E402

def _resolve_at_sentinels(e, start_ns: int, end_ns: int) -> None:
    """Replace @ start()/end() with the TOP-LEVEL query range bounds
    everywhere in the AST (upstream semantics: the sentinels always refer
    to the outer query, even inside subqueries)."""
    at = getattr(e, "at_ns", None)
    if at == "start":
        e.at_ns = start_ns
    elif at == "end":
        e.at_ns = end_ns
    for attr in ("expr", "selector", "lhs", "rhs", "param"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            _resolve_at_sentinels(child, start_ns, end_ns)
    for child in getattr(e, "args", ()) or ():
        if isinstance(child, Expr):
            _resolve_at_sentinels(child, start_ns, end_ns)


# functions that keep the metric name on their output
_KEEPS_NAME = {"sort", "sort_desc", "last_over_time"}


class EvalError(ValueError):
    pass


@dataclass
class Vector:
    """Evaluated instant-vector-per-step matrix."""

    labels: list[dict[bytes, bytes]]  # per series
    values: np.ndarray  # [S, n_steps]; NaN = no sample

    def drop_name(self) -> "Vector":
        return Vector(
            [{k: v for k, v in lb.items() if k != b"__name__"} for lb in self.labels],
            self.values,
        )


@dataclass
class Scalar:
    values: np.ndarray  # [n_steps]


@dataclass
class StringValue:
    value: str


class Engine:
    """Evaluates PromQL over a storage database namespace."""

    def __init__(self, db, namespace: str = "default",
                 lookback_ns: int = DEFAULT_LOOKBACK_NS,
                 limits: "QueryLimits | None" = None,
                 subquery_step_ns: int = 60 * NS,
                 resolve_tiers: bool = True,
                 now_fn=None,
                 query_compile: bool = False):
        import time as _time

        self.db = db
        self.namespace = namespace
        self.lookback_ns = lookback_ns
        # whole-query compilation (query/compiler.py, ROADMAP #2): fuse a
        # covered plan into one jit'd XLA program. Config-driven default;
        # M3_TPU_QUERY_COMPILE=1/0 is the runtime escape hatch either way
        self.query_compile = bool(query_compile)
        # retention-tier read resolution (aggregated namespaces); now_fn is
        # injectable so tests can expire raw retention deterministically
        self.resolve_tiers = resolve_tiers
        self.now_fn = now_fn or _time.time_ns
        # Budgets are enforced in the storage read path; an explicit limits
        # arg (re)binds the DATABASE-WIDE budget, mirroring the reference
        # where limits live in storage options, one set per node — so the
        # most recent binding governs every reader of this db.
        if limits is not None:
            db.limits = limits
        self.limits = limits or getattr(db, "limits", None) or QueryLimits()
        # default subquery resolution when [range:] omits the step
        # (upstream: the global evaluation interval)
        self.subquery_step_ns = subquery_step_ns
        # partial-result contract (PR-2): ReadWarnings every degraded
        # storage leg recorded during the LAST query, reset per query —
        # the HTTP layer turns these into M3-Warnings headers. THREAD-
        # LOCAL: the coordinator serves concurrent requests through one
        # Engine, and a shared field would leak query A's warnings into
        # query B's response (or hide A's entirely).
        import threading as _threading

        self._warn_tls = _threading.local()

    # -- public API --

    @property
    def last_warnings(self) -> list:
        """ReadWarnings from the last query evaluated ON THIS THREAD
        (reset per query). The HTTP handler reads this on the request
        thread that ran the query, so concurrent requests never observe
        each other's warnings."""
        return getattr(self._warn_tls, "last", [])

    @property
    def last_stats(self):
        """QueryStats of the last query evaluated ON THIS THREAD (same
        thread-local discipline as last_warnings): series matched, blocks
        read, bytes decoded, cache hit/miss, decode rungs, stage timings.
        The HTTP layer embeds it in the response envelope under `stats`."""
        return getattr(self._warn_tls, "last_stats", None)

    def _active_limits(self) -> "QueryLimits":
        """The CURRENT database-wide binding (storage accounting consults
        db.limits, so activation must target the same object even if
        another Engine rebound it after this one was constructed)."""
        return getattr(self.db, "limits", None) or self.limits

    def query_range(self, q: str, start_ns: int, end_ns: int, step_ns: int):
        return self.query_range_expr(promql.parse(q), start_ns, end_ns,
                                     step_ns, query_text=q)

    def query_range_expr(self, expr: Expr, start_ns: int, end_ns: int,
                         step_ns: int, query_text: str = ""):
        """Evaluate a pre-parsed AST (PromQL or any front-end compiling to
        it — M3QL, Graphite-on-tags) over the step grid."""
        if step_ns <= 0:
            raise EvalError("step must be positive")
        eval_ts = np.arange(start_ns, end_ns + 1, step_ns, dtype=np.int64)
        limits = self._active_limits()
        limits.check_steps(len(eval_ts))
        limits.start_query()
        from m3_tpu.utils import querystats, trace

        self._warn_tls.sink = sink = []
        st = querystats.start(query=query_text, namespace=self.namespace)
        try:
            with trace.span(trace.ENGINE_QUERY, steps=len(eval_ts)) as sp:
                if sp is not None:
                    st.trace_id = sp.trace_id
                with querystats.stage("eval"):
                    _resolve_at_sentinels(expr, int(eval_ts[0]),
                                          int(eval_ts[-1]))
                    out = self._maybe_compiled(expr, eval_ts)
                    if out is None:
                        out = self._eval(expr, eval_ts)
                    return out, eval_ts
        finally:
            querystats.finish(st)
            self._warn_tls.last_stats = st
            self._warn_tls.sink = None
            self._warn_tls.last = sink
            limits.end_query()

    def query_instant(self, q: str, t_ns: int):
        eval_ts = np.array([t_ns], dtype=np.int64)
        limits = self._active_limits()
        limits.start_query()
        from m3_tpu.utils import querystats, trace

        self._warn_tls.sink = sink = []
        st = querystats.start(query=q, namespace=self.namespace)
        try:
            with trace.span(trace.ENGINE_QUERY, steps=1) as sp:
                if sp is not None:
                    st.trace_id = sp.trace_id
                with querystats.stage("eval"):
                    expr = promql.parse(q)
                    _resolve_at_sentinels(expr, t_ns, t_ns)
                    out = self._maybe_compiled(expr, eval_ts)
                    if out is None:
                        out = self._eval(expr, eval_ts)
                    return out, eval_ts
        finally:
            querystats.finish(st)
            self._warn_tls.last_stats = st
            self._warn_tls.sink = None
            self._warn_tls.last = sink
            limits.end_query()

    def _compile_enabled(self) -> bool:
        """M3_TPU_QUERY_COMPILE overrides ('1' forces on, '0' forces
        off); otherwise the engine's configured default. Read per query
        so tests and operators can flip the hatch on a live process."""
        import os

        v = os.environ.get("M3_TPU_QUERY_COMPILE")
        if v == "1":
            return True
        if v == "0":
            return False
        return self.query_compile

    def _maybe_compiled(self, expr: Expr, eval_ts: np.ndarray):
        """Whole-query compiled evaluation (query/compiler.py) when
        enabled; None hands the query to the op-by-op interpreter —
        uncovered plan shapes fall back transparently (counted, never an
        error)."""
        if not self._compile_enabled():
            return None
        from m3_tpu.query import compiler

        return compiler.try_execute(self, expr, eval_ts)

    # -- fetch --

    def _resolve_ts(self, sel, eval_ts: np.ndarray) -> np.ndarray:
        """Selector evaluation timestamps: apply the @ modifier (pin every
        step to one instant) and then the offset. start()/end() sentinels
        were already resolved against the TOP-LEVEL query range at parse
        resolution — inside a subquery they must not see the inner grid."""
        at = getattr(sel, "at_ns", None)
        if at is not None:
            eval_ts = np.full_like(eval_ts, int(at))
        return eval_ts - sel.offset_ns

    def _fetch(self, sel: VectorSelector, eval_ts: np.ndarray, range_ns: int):
        """(labels, RaggedSeries) for samples covering the windows.

        Namespaces are chosen by tier resolution (query/resolver): a
        coarse-step read goes to the cheapest complete aggregated tier
        (resolve_read), and a range past raw retention reads the
        downsampled namespaces and stitches — the reference's
        aggregated-namespace fanout (cluster_resolver.go)."""
        shifted = self._resolve_ts(sel, eval_ts)
        t_min = int(shifted[0]) - max(range_ns, self.lookback_ns)
        t_max = int(shifted[-1]) + 1
        from m3_tpu.index.query import matchers_to_query
        from m3_tpu.query import resolver

        if self.resolve_tiers:
            step_ns = int(eval_ts[1] - eval_ts[0]) if len(eval_ts) > 1 else 0
            ns_list, tier_info = resolver.resolve_read(
                self.db, self.namespace, t_min, t_max, step_ns, range_ns,
                self.now_fn())
            self._record_tier_choice(tier_info)
        else:
            ns_list = [self.namespace]
        iq = matchers_to_query(sel.matchers)
        warn_sink = getattr(self._warn_tls, "sink", None)
        # version key sampled BEFORE the read: a write racing the fetch
        # can then only make the key stale (harmless hot-tier miss) —
        # sampling after would cache pre-write data under the post-write
        # version and serve it warm until the next bump
        fetch_key = self._fetch_key(sel, ns_list, t_min, t_max)
        ragged_res = resolver.fetch_tagged_ragged(
            self.db, ns_list, iq, t_min, t_max, warnings=warn_sink)
        if ragged_res is not None:
            # single-tier storage read: the CSR lands here straight from
            # the per-shard ragged finalize — no per-series tuples, no
            # concatenate; the compiler's slab prep consumes it as-is
            docs, times, vbits, offsets = ragged_res
            labels = [dict(doc.fields) for doc in docs]
            raws = RaggedSeries(times, vbits.view(np.float64), offsets)
        else:
            docs, series = resolver.fetch_tagged(
                self.db, ns_list, iq, t_min, t_max, warnings=warn_sink)
            labels = []
            per_series = []
            for doc, (times, vbits) in zip(docs, series):
                labels.append(dict(doc.fields))
                per_series.append((times, vbits.view(np.float64)))
            raws = RaggedSeries.from_lists(per_series)
        # hot-tier identity (storage/hottier.py): the fetch is fully
        # determined by (namespace versions, selector, range), so the
        # compiled path can key prepared device slabs on it
        raws.fetch_key = fetch_key
        return labels, raws

    def _record_tier_choice(self, info: dict) -> None:
        """Per-tier read counters (query.tier scope, {tier=mode/res}) +
        the explain `tiers` block: every selector fetch records which
        tier served it, so ?explain=analyze shows the routing and
        dashboards can watch aggregated-tier hit rates."""
        from m3_tpu.query import explain as explain_mod
        from m3_tpu.utils.instrument import default_registry

        tier = info.get("mode", "raw")
        if tier == "aggregated":
            res = int(info.get("resolution_ns", 0))
            tier = f"aggregated_{res // 1_000_000_000}s"
        default_registry().root_scope("query").subscope(
            "tier", tier=tier).counter("reads")
        col = explain_mod.current()
        if col is not None:
            col.add_tier(info)

    def _fetch_key(self, sel, ns_list, t_min: int, t_max: int):
        """Content-version key for one selector fetch, or None when any
        namespace lacks version tracking (cluster facades)."""
        parts = []
        for name in ns_list:
            try:
                ns = self.db.namespaces[name]
            except Exception:  # noqa: BLE001 - facade without the map
                return None
            if not getattr(ns, "has_version_truth", False):
                # facades (cluster, fanout) have no local version truth;
                # fanout would even DELEGATE data_version to its local
                # namespace, keying out remote-zone changes — no hot tier
                # (cluster facades still serve ragged reads, which is why
                # this is a separate marker from supports_ragged_read)
                return None
            parts.append((name, ns.ns_uid, ns.data_version()))
        mk = tuple(sorted((m.name, getattr(m.match_type, "value",
                                           str(m.match_type)), m.value)
                          for m in sel.matchers))
        return (tuple(parts), mk, sel.offset_ns,
                getattr(sel, "at_ns", None), t_min, t_max)

    # -- evaluation --

    def _eval(self, e: Expr, eval_ts: np.ndarray):
        """Evaluate one AST node. When an EXPLAIN collector is active on
        this thread (query/explain.py), the node also becomes one plan-
        tree entry carrying, in analyze mode, its wall time and the
        QueryStats deltas (series/blocks/bytes/rungs/remote legs) its
        subtree accrued; inactive, this is one thread-local read."""
        from m3_tpu.query import explain as explain_mod

        col = explain_mod.current()
        if col is None:
            return self._eval_node(e, eval_ts)
        with col.node(e):
            return self._eval_node(e, eval_ts)

    def _eval_node(self, e: Expr, eval_ts: np.ndarray):
        if isinstance(e, NumberLiteral):
            return Scalar(np.full(len(eval_ts), e.value))
        if isinstance(e, StringLiteral):
            return StringValue(e.value)
        if isinstance(e, VectorSelector):
            labels, raws = self._fetch(e, eval_ts, 0)
            vals = windows.instant_values(raws, self._resolve_ts(e, eval_ts),
                                          self.lookback_ns)
            return _compact(Vector(labels, vals))
        if isinstance(e, (MatrixSelector, SubqueryExpr)):
            raise EvalError("range vector must be an argument of a function")
        if isinstance(e, UnaryExpr):
            v = self._eval(e.expr, eval_ts)
            if e.op == "-":
                if isinstance(v, Scalar):
                    return Scalar(-v.values)
                return Vector(v.drop_name().labels, -v.values)
            return v
        if isinstance(e, Call):
            return self._eval_call(e, eval_ts)
        if isinstance(e, AggregateExpr):
            return self._eval_aggregate(e, eval_ts)
        if isinstance(e, BinaryExpr):
            return self._eval_binary(e, eval_ts)
        raise EvalError(f"cannot evaluate {type(e).__name__}")

    # -- functions --

    _RANGE_FNS = {
        "rate": ("extrap", True, True),
        "increase": ("extrap", True, False),
        "delta": ("extrap", False, False),
        "irate": ("instant", True, True),
        "idelta": ("instant", False, False),
    }
    _OVER_TIME = {
        "avg_over_time": "avg",
        "sum_over_time": "sum",
        "count_over_time": "count",
        "min_over_time": "min",
        "max_over_time": "max",
        "last_over_time": "last",
        "stddev_over_time": "stddev",
        "stdvar_over_time": "stdvar",
        "present_over_time": "present",
        "changes": "changes",
        "resets": "resets",
    }
    _MATH = {
        "abs": np.abs,
        "ceil": np.ceil,
        "floor": np.floor,
        "exp": np.exp,
        "ln": np.log,
        "log2": np.log2,
        "log10": np.log10,
        "sqrt": np.sqrt,
        "sgn": np.sign,
        "deg": np.degrees,
        "rad": np.radians,
        "sin": np.sin,
        "cos": np.cos,
        "tan": np.tan,
        "asin": np.arcsin,
        "acos": np.arccos,
        "atan": np.arctan,
        "sinh": np.sinh,
        "cosh": np.cosh,
        "tanh": np.tanh,
        "asinh": np.arcsinh,
        "acosh": np.arccosh,
        "atanh": np.arctanh,
    }
    # datetime component extractors over UTC second timestamps (upstream
    # promql functions.go dateWrapper family); 1970-01-01 was a Thursday
    _DATETIME = {
        "minute": lambda s, D, M, Y: (s // 60) % 60,
        "hour": lambda s, D, M, Y: (s // 3600) % 24,
        "day_of_week": lambda s, D, M, Y: (D.astype(np.int64) + 4) % 7,
        "day_of_month": lambda s, D, M, Y: (
            D - M.astype("datetime64[D]")).astype(np.int64) + 1,
        "day_of_year": lambda s, D, M, Y: (
            D - Y.astype("datetime64[D]")).astype(np.int64) + 1,
        "days_in_month": lambda s, D, M, Y: (
            (M + 1).astype("datetime64[D]")
            - M.astype("datetime64[D]")).astype(np.int64),
        "month": lambda s, D, M, Y: (M - Y).astype(np.int64) + 1,
        "year": lambda s, D, M, Y: Y.astype(np.int64) + 1970,
    }

    def _range_arg(self, e: Call, idx: int = 0):
        if len(e.args) <= idx or not isinstance(
            e.args[idx], (MatrixSelector, SubqueryExpr)
        ):
            raise EvalError(f"{e.func}() expects a range vector argument")
        return e.args[idx]

    def _eval_range_arg(self, arg, eval_ts: np.ndarray):
        """(labels, RaggedSeries, shifted_eval_ts, range_ns) for a range
        vector argument — a plain matrix selector fetch, or a SUBQUERY
        evaluated at step-aligned instants and rewrapped as ragged samples
        so every temporal function runs unchanged on it."""
        if isinstance(arg, MatrixSelector):
            # matrix selectors are consumed here rather than via _eval, so
            # give the plan tree its selector stage explicitly (the
            # selector → range function → aggregation shape)
            import contextlib

            from m3_tpu.query import explain as explain_mod

            col = explain_mod.current()
            with col.node(arg) if col is not None \
                    else contextlib.nullcontext():
                labels, raws = self._fetch(arg.selector, eval_ts,
                                           arg.range_ns)
            return labels, raws, self._resolve_ts(arg.selector, eval_ts), arg.range_ns
        # subquery: evaluate the inner expr once over the union of aligned
        # instants covering every parent step's window
        shifted = self._resolve_ts(arg, eval_ts)
        step = arg.step_ns or self.subquery_step_ns
        lo = int(shifted.min()) - arg.range_ns
        hi = int(shifted.max())
        first = (lo // step + 1) * step  # first aligned instant > lo
        last = (hi // step) * step
        if last < first:
            grid = np.array([first], dtype=np.int64)
        else:
            grid = np.arange(first, last + 1, step, dtype=np.int64)
        self.limits.check_steps(len(grid))
        inner = self._eval(arg.expr, grid)
        if not isinstance(inner, Vector):
            raise EvalError("subquery requires an instant-vector expression")
        per_series = []
        labels = []
        for i, lb in enumerate(inner.labels):
            row = inner.values[i]
            keep = ~np.isnan(row)
            if not keep.any():
                continue
            labels.append(lb)
            per_series.append((grid[keep], row[keep]))
        return labels, RaggedSeries.from_lists(per_series), shifted, arg.range_ns

    def _eval_call(self, e: Call, eval_ts: np.ndarray):
        fn = e.func
        if fn in self._RANGE_FNS:
            kind, is_counter, is_rate = self._RANGE_FNS[fn]
            labels, raws, shifted, range_ns = self._eval_range_arg(
                self._range_arg(e), eval_ts)
            if kind == "extrap":
                vals = windows.extrapolated_rate(raws, shifted, range_ns,
                                                 is_counter, is_rate)
            else:
                vals = windows.instant_delta(raws, shifted, range_ns,
                                             is_counter, is_rate)
            return _compact(Vector(labels, vals).drop_name())
        if fn in self._OVER_TIME:
            labels, raws, shifted, range_ns = self._eval_range_arg(
                self._range_arg(e), eval_ts)
            vals = windows.over_time(self._OVER_TIME[fn], raws, shifted, range_ns)
            out = Vector(labels, vals)
            return _compact(out if fn in _KEEPS_NAME else out.drop_name())
        if fn == "holt_winters":
            labels, raws, shifted, range_ns = self._eval_range_arg(
                self._range_arg(e), eval_ts)
            sf = self._scalar_param(e.args[1], eval_ts)
            tf = self._scalar_param(e.args[2], eval_ts)
            if not (0 < sf < 1) or not (0 < tf <= 1):
                raise EvalError("holt_winters smoothing factors must be in "
                                "(0, 1)")
            vals = windows.holt_winters(raws, shifted, range_ns, sf, tf)
            return _compact(Vector(labels, vals).drop_name())
        if fn == "absent_over_time":
            arg = self._range_arg(e)
            labels, raws, shifted, range_ns = self._eval_range_arg(arg, eval_ts)
            present_m = windows.over_time("present", raws, shifted, range_ns)
            present = ((~np.isnan(present_m)).any(axis=0) if len(labels)
                       else np.zeros(len(eval_ts), bool))
            lbls = (_absent_labels(arg.selector)
                    if isinstance(arg, MatrixSelector) else {})
            return Vector([lbls], np.where(present, np.nan, 1.0)[None, :])
        if fn == "quantile_over_time":
            phi = self._scalar_param(e.args[0], eval_ts)
            labels, raws, shifted, range_ns = self._eval_range_arg(
                self._range_arg(e, 1), eval_ts)
            vals = _quantile_over_time(raws, shifted, range_ns, phi)
            return _compact(Vector(labels, vals).drop_name())
        if fn in ("deriv", "predict_linear"):
            labels, raws, shifted, range_ns = self._eval_range_arg(
                self._range_arg(e), eval_ts)
            off = None
            if fn == "predict_linear":
                off = self._scalar_param(e.args[1], eval_ts)
            vals = windows.linear_regression(raws, shifted, range_ns, off)
            return _compact(Vector(labels, vals).drop_name())
        if fn in self._MATH:
            v = self._eval(e.args[0], eval_ts)
            if isinstance(v, Scalar):
                return Scalar(self._MATH[fn](v.values))
            return Vector(v.drop_name().labels, self._MATH[fn](v.values))
        if fn == "round":
            v = self._eval(e.args[0], eval_ts)
            to = self._scalar_param(e.args[1], eval_ts) if len(e.args) > 1 else 1.0
            # round half away from... Prometheus rounds half up via floor(v/to+0.5)
            vals = np.floor(v.values / to + 0.5) * to
            return Vector(v.drop_name().labels, vals)
        if fn in ("clamp", "clamp_min", "clamp_max"):
            v = self._eval(e.args[0], eval_ts)
            vals = v.values
            if fn == "clamp":
                lo = self._scalar_param(e.args[1], eval_ts)
                hi = self._scalar_param(e.args[2], eval_ts)
                vals = np.clip(vals, lo, hi)
            elif fn == "clamp_min":
                vals = np.maximum(vals, self._scalar_param(e.args[1], eval_ts))
            else:
                vals = np.minimum(vals, self._scalar_param(e.args[1], eval_ts))
            return Vector(v.drop_name().labels, vals)
        if fn == "scalar":
            v = self._eval(e.args[0], eval_ts)
            if not isinstance(v, Vector):
                raise EvalError("scalar() expects an instant vector")
            n_valid = (~np.isnan(v.values)).sum(axis=0)
            one = (n_valid == 1)
            summed = np.nansum(v.values, axis=0)
            return Scalar(np.where(one, summed, np.nan))
        if fn == "vector":
            s = self._eval(e.args[0], eval_ts)
            if not isinstance(s, Scalar):
                raise EvalError("vector() expects a scalar")
            return Vector([{}], s.values[None, :])
        if fn == "time":
            return Scalar(eval_ts.astype(np.float64) / NS)
        if fn == "pi":
            return Scalar(np.full(len(eval_ts), math.pi))
        if fn in self._DATETIME:
            if e.args:
                v = self._eval(e.args[0], eval_ts)
                if not isinstance(v, Vector):
                    raise EvalError(f"{fn}() expects an instant vector")
                labels = v.drop_name().labels
                vals = v.values
            else:
                # no argument: the evaluation timestamps themselves
                labels = [{}]
                vals = (eval_ts.astype(np.float64) / NS)[None, :]
            secs = np.floor(vals)
            safe = np.where(np.isnan(secs), 0, secs).astype(np.int64)
            dt = safe.astype("datetime64[s]")
            D = dt.astype("datetime64[D]")
            M = dt.astype("datetime64[M]")
            Y = dt.astype("datetime64[Y]")
            out = self._DATETIME[fn](safe, D, M, Y).astype(np.float64)
            return Vector(labels, np.where(np.isnan(vals), np.nan, out))
        if fn == "timestamp":
            v = self._eval(e.args[0], eval_ts)
            ts = np.broadcast_to(eval_ts.astype(np.float64) / NS, v.values.shape)
            return Vector(v.drop_name().labels, np.where(np.isnan(v.values), np.nan, ts))
        if fn == "absent":
            v = self._eval(e.args[0], eval_ts)
            present = (~np.isnan(v.values)).any(axis=0) if len(v.labels) else np.zeros(
                len(eval_ts), bool
            )
            lbls = _absent_labels(e.args[0])
            return Vector([lbls], np.where(present, np.nan, 1.0)[None, :])
        if fn == "histogram_quantile":
            phi = self._scalar_param(e.args[0], eval_ts)
            v = self._eval(e.args[1], eval_ts)
            return _histogram_quantile(phi, v)
        if fn == "label_replace":
            v = self._eval(e.args[0], eval_ts)
            dst, repl, src, rx = (a.value for a in e.args[1:5])
            pattern = re.compile(rx)
            # RE2 $1/${name} replacement syntax -> Python \1/\g<name>
            py_repl = re.sub(
                r"\$(\d+|\{(\w+)\})",
                lambda m: f"\\g<{m.group(2)}>" if m.group(2) else f"\\{m.group(1)}",
                repl.replace("$$", "\x00"),
            ).replace("\x00", "$")
            out_labels = []
            for lb in v.labels:
                lb = dict(lb)
                val = lb.get(src.encode(), b"").decode()
                m = pattern.fullmatch(val)
                if m:
                    new = m.expand(py_repl).encode() if repl else b""
                    if new:
                        lb[dst.encode()] = new
                    else:
                        lb.pop(dst.encode(), None)
                out_labels.append(lb)
            return Vector(out_labels, v.values)
        if fn == "label_join":
            v = self._eval(e.args[0], eval_ts)
            dst = e.args[1].value
            sep = e.args[2].value
            srcs = [a.value for a in e.args[3:]]
            out_labels = []
            for lb in v.labels:
                lb = dict(lb)
                joined = sep.join(lb.get(s.encode(), b"").decode() for s in srcs)
                if joined:
                    lb[dst.encode()] = joined.encode()
                else:
                    lb.pop(dst.encode(), None)
                out_labels.append(lb)
            return Vector(out_labels, v.values)
        if fn in ("sort", "sort_desc"):
            v = self._eval(e.args[0], eval_ts)
            if len(v.labels) and v.values.shape[1]:
                key = np.where(np.isnan(v.values[:, -1]), -np.inf, v.values[:, -1])
                order = np.argsort(-key if fn == "sort_desc" else key, kind="stable")
                return Vector([v.labels[i] for i in order], v.values[order])
            return v
        raise EvalError(f"unknown function {fn}()")

    def _scalar_param(self, e: Expr, eval_ts: np.ndarray) -> float:
        v = self._eval(e, eval_ts)
        if isinstance(v, Scalar):
            return float(v.values[0])
        raise EvalError("expected scalar parameter")

    # -- aggregation --

    def _eval_aggregate(self, e: AggregateExpr, eval_ts: np.ndarray):
        v = self._eval(e.expr, eval_ts)
        if not isinstance(v, Vector):
            raise EvalError(f"{e.op} expects an instant vector")
        S, T = v.values.shape if len(v.labels) else (0, len(eval_ts))
        keys, out_labels_for = grouping_keys(v.labels, e.grouping, e.without)
        uniq = sorted(set(keys))
        gid = {k: i for i, k in enumerate(uniq)}
        groups = np.array([gid[k] for k in keys], np.int64) if keys else np.empty(0, np.int64)
        G = len(uniq)
        vals = v.values if S else np.zeros((0, T))
        nan = np.isnan(vals)
        filled0 = np.where(nan, 0.0, vals)

        def seg(arr, init=0.0, op="add"):
            out = np.full((G, T), init)
            if op == "add":
                np.add.at(out, groups, arr)
            elif op == "min":
                np.minimum.at(out, groups, arr)
            elif op == "max":
                np.maximum.at(out, groups, arr)
            return out

        count = seg((~nan).astype(np.float64))
        any_present = count > 0
        op = e.op
        if op in ("sum", "avg", "stddev", "stdvar"):
            s1 = seg(filled0)
            if op == "sum":
                out = s1
            else:
                mean = s1 / np.where(any_present, count, 1)
                if op == "avg":
                    out = mean
                else:
                    s2 = seg(np.where(nan, 0.0, vals * vals))
                    var = np.maximum(s2 / np.where(any_present, count, 1) - mean**2, 0)
                    out = var if op == "stdvar" else np.sqrt(var)
        elif op == "count":
            out = count
        elif op == "min":
            out = seg(np.where(nan, np.inf, vals), np.inf, "min")
        elif op == "max":
            out = seg(np.where(nan, -np.inf, vals), -np.inf, "max")
        elif op == "group":
            out = np.ones((G, T))
        elif op == "quantile":
            phi = self._scalar_param(e.param, eval_ts)
            out = np.full((G, T), np.nan)
            for g in range(G):
                sub = vals[groups == g]
                out[g] = _quantile_cols(sub, phi)
        elif op in ("topk", "bottomk"):
            k = int(self._scalar_param(e.param, eval_ts))
            keep = np.zeros_like(vals, dtype=bool)
            for g in range(G):
                rows = np.nonzero(groups == g)[0]
                sub = vals[rows]
                for t in range(T):
                    col = sub[:, t]
                    valid = np.nonzero(~np.isnan(col))[0]
                    if len(valid) == 0:
                        continue
                    order = np.argsort(col[valid], kind="stable")
                    sel = (order[::-1] if op == "topk" else order)[:k]
                    keep[rows[valid[sel]], t] = True
            return _compact(Vector(
                [dict(lb) for lb in v.labels], np.where(keep, vals, np.nan)
            ))
        elif op == "count_values":
            if not isinstance(e.param, StringLiteral) and not isinstance(
                self._eval(e.param, eval_ts), StringValue
            ):
                raise EvalError("count_values expects a string label parameter")
            label = (
                e.param.value if isinstance(e.param, StringLiteral)
                else self._eval(e.param, eval_ts).value
            ).encode()
            bucket: dict[tuple, np.ndarray] = {}
            out_lbls: dict[tuple, dict] = {}
            for s in range(S):
                for t in range(T):
                    x = vals[s, t]
                    if np.isnan(x):
                        continue
                    vkey = keys[s] + ((label, _fmt(x).encode()),)
                    if vkey not in bucket:
                        bucket[vkey] = np.full(T, np.nan)
                        lb = dict(out_labels_for[keys[s]])
                        lb[label] = _fmt(x).encode()
                        out_lbls[vkey] = lb
                    cur = bucket[vkey][t]
                    bucket[vkey][t] = 1.0 if np.isnan(cur) else cur + 1.0
            ks = sorted(bucket)
            return Vector([out_lbls[k] for k in ks],
                          np.stack([bucket[k] for k in ks]) if ks else np.zeros((0, T)))
        else:
            raise EvalError(f"unknown aggregator {op}")
        out = np.where(any_present, out, np.nan)
        return _compact(Vector([dict(out_labels_for[k]) for k in uniq], out))

    # -- binary ops --

    def _eval_binary(self, e: BinaryExpr, eval_ts: np.ndarray):
        lhs = self._eval(e.lhs, eval_ts)
        rhs = self._eval(e.rhs, eval_ts)
        op = e.op
        if isinstance(lhs, Scalar) and isinstance(rhs, Scalar):
            out = _apply_op(op, lhs.values, rhs.values)
            if op in promql.COMPARISONS:
                if not e.bool_mode:
                    raise EvalError("comparisons between scalars must use bool")
                out = out.astype(np.float64)
            return Scalar(out)
        if op in ("and", "or", "unless"):
            return self._set_op(op, lhs, rhs, e.matching)
        if isinstance(lhs, Scalar) or isinstance(rhs, Scalar):
            vec, sc = (rhs, lhs) if isinstance(lhs, Scalar) else (lhs, rhs)
            swapped = isinstance(lhs, Scalar)
            a = sc.values[None, :] if swapped else vec.values
            b = vec.values if swapped else sc.values[None, :]
            raw = _apply_op(op, a, b)
            if op in promql.COMPARISONS:
                if e.bool_mode:
                    vals = np.where(np.isnan(vec.values), np.nan, raw.astype(np.float64))
                    return _compact(Vector(vec.drop_name().labels, vals))
                vals = np.where(raw.astype(bool), vec.values, np.nan)
                return _compact(Vector(vec.labels, vals))
            return _compact(Vector(vec.drop_name().labels, raw))
        # vector-vector
        return self._vector_binary(e, lhs, rhs)

    def _match_key(self, lb: dict, matching: VectorMatching | None):
        if matching and matching.on:
            items = [(k, lb[k]) for k in sorted(l.encode() for l in matching.labels)
                     if k in lb]
        else:
            excl = {b"__name__"}
            if matching:
                excl |= {l.encode() for l in matching.labels}
            items = sorted((k, v) for k, v in lb.items() if k not in excl)
        return tuple(items)

    def _set_op(self, op, lhs, rhs, matching):
        if not isinstance(lhs, Vector) or not isinstance(rhs, Vector):
            raise EvalError(f"set operator {op} requires vectors")
        rkeys = {self._match_key(lb, matching): i for i, lb in enumerate(rhs.labels)}
        T = lhs.values.shape[1] if len(lhs.labels) else rhs.values.shape[1] if len(rhs.labels) else 0
        if op == "and":
            out_l, out_v = [], []
            for i, lb in enumerate(lhs.labels):
                j = rkeys.get(self._match_key(lb, matching))
                if j is not None:
                    mask = ~np.isnan(rhs.values[j])
                    out_l.append(lb)
                    out_v.append(np.where(mask, lhs.values[i], np.nan))
            return _compact(Vector(out_l, np.stack(out_v) if out_v else np.zeros((0, T))))
        if op == "unless":
            out_l, out_v = [], []
            for i, lb in enumerate(lhs.labels):
                j = rkeys.get(self._match_key(lb, matching))
                vals = lhs.values[i]
                if j is not None:
                    vals = np.where(np.isnan(rhs.values[j]), vals, np.nan)
                out_l.append(lb)
                out_v.append(vals)
            return _compact(Vector(out_l, np.stack(out_v) if out_v else np.zeros((0, T))))
        # or
        out_l = [dict(lb) for lb in lhs.labels]
        out_v = [lhs.values[i] for i in range(len(lhs.labels))]
        lkeys = {self._match_key(lb, matching) for lb in lhs.labels}
        lcover = {}
        for i, lb in enumerate(lhs.labels):
            k = self._match_key(lb, matching)
            cov = ~np.isnan(lhs.values[i])
            lcover[k] = cov | lcover.get(k, np.zeros_like(cov))
        for j, lb in enumerate(rhs.labels):
            k = self._match_key(lb, matching)
            if k not in lkeys:
                out_l.append(dict(lb))
                out_v.append(rhs.values[j])
            else:
                gap = np.isnan(rhs.values[j]) | lcover[k]
                extra = np.where(gap, np.nan, rhs.values[j])
                if not np.isnan(extra).all():
                    out_l.append(dict(lb))
                    out_v.append(extra)
        return _compact(Vector(out_l, np.stack(out_v) if out_v else np.zeros((0, T))))

    def _vector_binary(self, e: BinaryExpr, lhs: Vector, rhs: Vector):
        m = e.matching
        group_left = m.group_left if m else False
        group_right = m.group_right if m else False
        if group_right:
            # evaluate as mirrored group_left
            sym = {"<": ">", ">": "<", "<=": ">=", ">=": "<=",
                   "/": None, "-": None, "%": None, "^": None}
            swapped_op = sym.get(e.op, e.op)
            if swapped_op is None:
                lhs, rhs = rhs, lhs  # keep op, swap operand roles manually below
                group_left, group_right = True, False
                flip = True
            else:
                lhs, rhs = rhs, lhs
                e = BinaryExpr(swapped_op, e.lhs, e.rhs, e.bool_mode, e.matching)
                group_left, group_right = True, False
                flip = False
        else:
            flip = False

        rmap: dict[tuple, int] = {}
        for j, lb in enumerate(rhs.labels):
            k = self._match_key(lb, m)
            if k in rmap:
                raise EvalError("many-to-many vector matching: duplicate series on 'one' side")
            rmap[k] = j
        out_l, out_v = [], []
        seen: dict[tuple, int] = {}
        for i, lb in enumerate(lhs.labels):
            k = self._match_key(lb, m)
            j = rmap.get(k)
            if j is None:
                continue
            if not group_left:
                if k in seen:
                    raise EvalError("many-to-one matching requires group_left/group_right")
                seen[k] = i
            a, b = lhs.values[i], rhs.values[j]
            if flip:
                a, b = b, a
            raw = _apply_op(e.op, a, b)
            if e.op in promql.COMPARISONS:
                if e.bool_mode:
                    vals = np.where(np.isnan(a) | np.isnan(b), np.nan,
                                    raw.astype(np.float64))
                    out_lb = self._result_labels(lb, rhs.labels[j], m, group_left)
                else:
                    vals = np.where(raw.astype(bool), lhs.values[i], np.nan)
                    out_lb = dict(lb)
            else:
                vals = raw
                out_lb = self._result_labels(lb, rhs.labels[j], m, group_left)
            out_l.append(out_lb)
            out_v.append(vals)
        T = lhs.values.shape[1] if len(lhs.labels) else (
            rhs.values.shape[1] if len(rhs.labels) else 0
        )
        return _compact(Vector(out_l, np.stack(out_v) if out_v else np.zeros((0, T))))

    def _result_labels(self, l_lb, r_lb, m: VectorMatching | None, group_left: bool):
        """Result labels per upstream: one-to-one on(...) keeps only the on
        labels; otherwise the (many-side) lhs labels minus __name__ and
        minus ignoring(...); group_left keeps the FULL many-side label set
        (minus __name__) plus any include labels copied from the one side."""
        if group_left:
            out = {k: v for k, v in l_lb.items() if k != b"__name__"}
        elif m and m.on:
            out = {k: v for k, v in l_lb.items() if k.decode() in m.labels}
        else:
            excl = {l.encode() for l in (m.labels if m else ())} | {b"__name__"}
            out = {k: v for k, v in l_lb.items() if k not in excl}
        for inc in (m.include if m else ()):
            k = inc.encode()
            if k in r_lb:
                out[k] = r_lb[k]
            else:
                out.pop(k, None)
        return out


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def grouping_keys(labels, grouping, without: bool):
    """Aggregation group keys: (per-series sorted-item key tuples, key ->
    kept-label dict). ONE definition of the by/without key semantics —
    the interpreter's _eval_aggregate and the whole-query compiler's
    _group_ids both build their group ids from this, so the compiled
    path cannot drift from the interpreter on grouping."""
    keys = []
    out_labels_for = {}
    for lb in labels:
        if without:
            kept = {
                k: val for k, val in lb.items()
                if k != b"__name__" and k.decode() not in grouping
            }
        elif grouping:
            kept = {k: val for k, val in lb.items() if k.decode() in grouping}
        else:
            kept = {}
        key = tuple(sorted(kept.items()))
        keys.append(key)
        out_labels_for[key] = kept
    return keys, out_labels_for


def _apply_op(op: str, a, b):
    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return np.fmod(a, b)
        if op == "^":
            return np.power(a, b)
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == ">":
            return a > b
        if op == "<":
            return a < b
        if op == ">=":
            return a >= b
        if op == "<=":
            return a <= b
    raise EvalError(f"unknown operator {op}")


def _compact(v: Vector) -> Vector:
    """Drop series with no samples at any step."""
    if not len(v.labels):
        return v
    keep = ~np.isnan(v.values).all(axis=1)
    if keep.all():
        return v
    idx = np.nonzero(keep)[0]
    return Vector([v.labels[i] for i in idx], v.values[idx])


def _quantile_cols(sub: np.ndarray, phi: float) -> np.ndarray:
    """Prometheus-style interpolated quantile down columns, NaN-aware."""
    T = sub.shape[1]
    out = np.full(T, np.nan)
    for t in range(T):
        col = sub[:, t]
        col = col[~np.isnan(col)]
        if len(col) == 0:
            continue
        if phi < 0:
            out[t] = -np.inf
            continue
        if phi > 1:
            out[t] = np.inf
            continue
        s = np.sort(col)
        rank = phi * (len(s) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(s) - 1)
        out[t] = s[lo] + (rank - lo) * (s[hi] - s[lo])
    return out


def _quantile_over_time(raws: RaggedSeries, eval_ts, range_ns, phi):
    lo, hi = raws.window_bounds(eval_ts, range_ns)
    out = np.full(lo.shape, np.nan)
    for s in range(lo.shape[0]):
        for t in range(lo.shape[1]):
            w = raws.values[lo[s, t] : hi[s, t]]
            if len(w) == 0:
                continue
            out[s, t] = _quantile_cols(w[:, None], phi)[0]
    return out


def _histogram_quantile(phi: float, v: Vector) -> Vector:
    groups: dict[tuple, list[int]] = {}
    lbls_for: dict[tuple, dict] = {}
    for i, lb in enumerate(v.labels):
        key = tuple(sorted(
            (k, val) for k, val in lb.items() if k not in (b"le", b"__name__")
        ))
        groups.setdefault(key, []).append(i)
        lbls_for[key] = {k: val for k, val in lb.items()
                         if k not in (b"le", b"__name__")}
    T = v.values.shape[1] if len(v.labels) else 0
    out_l, out_v = [], []
    for key, rows in sorted(groups.items()):
        les = []
        for i in rows:
            le_raw = v.labels[i].get(b"le", b"")
            try:
                les.append(float(le_raw))
            except ValueError:
                les.append(np.nan)
        order = np.argsort(les)
        les_sorted = np.array(les)[order]
        counts = v.values[[rows[int(o)] for o in order]]
        vals = np.full(T, np.nan)
        if len(les_sorted) >= 2 and np.isinf(les_sorted[-1]):
            # monotonize cumulative counts then interpolate
            counts = np.maximum.accumulate(np.where(np.isnan(counts), 0, counts), axis=0)
            total = counts[-1]
            with np.errstate(invalid="ignore", divide="ignore"):
                for t in range(T):
                    obs = total[t]
                    if not obs > 0:
                        continue
                    rank = phi * obs
                    b = int(np.searchsorted(counts[:, t], rank, side="left"))
                    b = min(b, len(les_sorted) - 1)
                    if b == len(les_sorted) - 1:
                        vals[t] = les_sorted[-2]
                        continue
                    if b == 0 and les_sorted[0] <= 0:
                        vals[t] = les_sorted[0]
                        continue
                    b_start = 0.0 if b == 0 else les_sorted[b - 1]
                    b_end = les_sorted[b]
                    cnt = counts[b, t] - (0.0 if b == 0 else counts[b - 1, t])
                    r = rank - (0.0 if b == 0 else counts[b - 1, t])
                    if cnt <= 0:
                        vals[t] = b_end
                    else:
                        vals[t] = b_start + (b_end - b_start) * (r / cnt)
        out_l.append(lbls_for[key])
        out_v.append(vals)
    return _compact(Vector(out_l, np.stack(out_v) if out_v else np.zeros((0, T))))


def _absent_labels(e: Expr) -> dict:
    if isinstance(e, VectorSelector):
        out = {}
        from m3_tpu.index.query import MatchType

        for m in e.matchers:
            if m.match_type == MatchType.EQUAL and m.name != b"__name__":
                out[m.name] = m.value
        return out
    return {}


def _fmt(x: float) -> str:
    if x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(x)
