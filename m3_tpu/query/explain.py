"""PromQL EXPLAIN / EXPLAIN ANALYZE: the engine's resolved plan as a
structured tree, with per-stage attribution.

Role parity with a SQL engine's EXPLAIN over the reference's executor
pipeline (the transform-node DAG in
/root/reference/src/query/executor/state.go, which the reference never
surfaced to operators): every `Engine._eval` node — selector, range
function, aggregation, binary op — becomes one plan node. In ANALYZE mode
each node additionally carries what THAT stage cost:

- wall time (inclusive of children — subtracting children gives self
  time, the tree keeps both derivable);
- series / blocks / bytes / cache hits+misses, diffed from the active
  QueryStats record around the node's evaluation;
- the decode/aggregate dispatch rung(s) that served it (device / native /
  scalar / cache), diffed the same way;
- for fan-out stages, one child leg PER REMOTE NODE (host, calls, ms,
  rows — recorded by the client session), so a cluster query's plan is
  the stitched CROSS-NODE tree: the same flat-list + parent-pointer
  machinery /debug/traces uses (trace.build_tree) nests it, and the
  record carries the trace id so the plan links to the stitched span
  tree.

Activation is a thread-local collector (`with explain.collect(analyze):`)
so the shared Engine needs no signature change and concurrent requests
never see each other's plans; an inactive engine pays one thread-local
read per AST node. Analyzed plans land in a bounded ring served at
/debug/explain (the slow-query-ring shape), and the query endpoints embed
the plan in the response envelope under `explain` when `?explain=plan` or
`?explain=analyze` is set.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager

from m3_tpu.utils import querystats, trace

_tls = threading.local()

_ring_lock = threading.Lock()
_ring: deque[dict] = deque(maxlen=64)

# bounded plan ring on the saturation plane (m3lint inv-queue-gauge)
from m3_tpu.utils import instrument as _instrument  # noqa: E402

_instrument.monitor_queue("explain_ring", lambda: len(_ring), _ring.maxlen)


def current() -> "Collector | None":
    """The thread's active plan collector (None outside EXPLAIN)."""
    return getattr(_tls, "collector", None)


@contextmanager
def collect(analyze: bool = True):
    """Install a plan collector for the scope of one engine evaluation."""
    prev = getattr(_tls, "collector", None)
    col = Collector(analyze)
    _tls.collector = col
    try:
        yield col
    finally:
        _tls.collector = prev


def describe(e) -> str:
    """One-line resolved description of an AST node (the `detail` field)."""
    from m3_tpu.query.promql import (
        AggregateExpr,
        BinaryExpr,
        Call,
        MatrixSelector,
        NumberLiteral,
        StringLiteral,
        SubqueryExpr,
        UnaryExpr,
        VectorSelector,
    )

    if isinstance(e, VectorSelector):
        parts = [f"{m.name.decode(errors='replace')}"
                 f"{m.match_type.value}"
                 f"{m.value.decode(errors='replace')!r}" for m in e.matchers]
        sel = "{" + ",".join(parts) + "}"
        if e.offset_ns:
            sel += f" offset {e.offset_ns / 1e9:g}s"
        return sel
    if isinstance(e, MatrixSelector):
        return f"{describe(e.selector)}[{e.range_ns / 1e9:g}s]"
    if isinstance(e, SubqueryExpr):
        step = f":{e.step_ns / 1e9:g}s" if e.step_ns else ":"
        return f"[{e.range_ns / 1e9:g}s{step}]"
    if isinstance(e, Call):
        return f"{e.func}()"
    if isinstance(e, AggregateExpr):
        by = ""
        if e.grouping:
            by = (" without " if e.without else " by ") \
                + "(" + ",".join(e.grouping) + ")"
        return f"{e.op}{by}"
    if isinstance(e, BinaryExpr):
        return e.op + (" bool" if e.bool_mode else "")
    if isinstance(e, UnaryExpr):
        return e.op
    if isinstance(e, NumberLiteral):
        return f"{e.value:g}"
    if isinstance(e, StringLiteral):
        return repr(e.value)
    return type(e).__name__


def kind(e) -> str:
    """Plan-node kind: the stage of the selector → range function →
    aggregation pipeline this AST node plays."""
    from m3_tpu.query.promql import (
        AggregateExpr,
        BinaryExpr,
        Call,
        MatrixSelector,
        NumberLiteral,
        StringLiteral,
        SubqueryExpr,
        UnaryExpr,
        VectorSelector,
    )

    if isinstance(e, (VectorSelector, MatrixSelector)):
        return "selector"
    if isinstance(e, SubqueryExpr):
        return "subquery"
    if isinstance(e, Call):
        from m3_tpu.query.engine import Engine

        return "range_fn" if e.func in Engine._RANGE_FNS \
            or e.func in Engine._OVER_TIME else "call"
    if isinstance(e, AggregateExpr):
        return "aggregate"
    if isinstance(e, BinaryExpr):
        return "binary"
    if isinstance(e, UnaryExpr):
        return "unary"
    if isinstance(e, (NumberLiteral, StringLiteral)):
        return "literal"
    return "expr"


class Collector:
    """Builds the plan as a FLAT list of span-shaped entries
    (span_id/parent_span_id) nested at the end by trace.build_tree — the
    exact dedupe/stitch machinery the cross-process trace endpoint uses,
    so remote legs merge in as ordinary entries."""

    def __init__(self, analyze: bool):
        self.analyze = analyze
        self.entries: list[dict] = []
        self._stack: list[dict] = []
        self._n = 0
        # whole-query compilation outcome (query/compiler.py): set once
        # per query — {"ran": bool, "cache_key": ..., "cache": hit|miss}
        # or {"ran": False, "reason": ...} on fallback
        self.compiled: dict | None = None
        # retention-tier routing (query/resolver.resolve_read): one
        # record per selector fetch — {"mode": aggregated|raw|stitched|
        # pinned_raw, "namespaces": [...], resolution/step when routed}
        self.tiers: list[dict] = []
        # legs already attributed to a (descendant) plan node: children
        # exit before parents, so a parent only claims what its subtree
        # hasn't — the selector gets the rpc legs, not every ancestor
        self._claimed: dict[str, tuple] = {}

    def _new_entry(self, node_kind: str, detail: str) -> dict:
        nid = f"plan-{self._n}"
        self._n += 1
        entry = {
            "span_id": nid,
            "parent_span_id": self._stack[-1]["span_id"] if self._stack
            else None,
            "node": node_kind,
            "detail": detail,
        }
        self.entries.append(entry)
        return entry

    @contextmanager
    def node(self, expr):
        """Wrap one engine evaluation node; in analyze mode, diff the
        active QueryStats record around it to attribute cost."""
        entry = self._new_entry(kind(expr), describe(expr))
        st = querystats.current() if self.analyze else None
        if st is not None:
            before = (st.series_matched, st.blocks_read, st.bytes_decoded,
                      st.cache_hits, st.cache_misses,
                      dict(st.decode_rungs), dict(st.node_legs),
                      dict(self._claimed),
                      (st.pipeline_groups, st.pipeline_wall_s,
                       dict(st.pipeline_stage_s)),
                      (st.index_segments, st.index_device_segments,
                       dict(st.index_fallback), st.index_terms_scanned,
                       st.index_terms_prefiltered, st.index_postings_rows))
        t0 = time.perf_counter()
        self._stack.append(entry)
        try:
            yield entry
        finally:
            self._stack.pop()
            dt = time.perf_counter() - t0
            if self.analyze:
                entry["duration_ms"] = round(dt * 1e3, 3)
            if st is not None:
                self._attribute(entry, st, before)

    def _attribute(self, entry: dict, st, before) -> None:
        (series0, blocks0, bytes0, hits0, miss0, rungs0, legs0,
         claimed0, pipe0, idx0) = before
        # postings-walk account this node's subtree accrued (the
        # selector's label matching: index/executor.py + index/device.py)
        iseg0, idev0, ifb0, iscan0, ipre0, irows0 = idx0
        d_segs = st.index_segments - iseg0
        if d_segs > 0:
            d_fb = {r: c - ifb0.get(r, 0)
                    for r, c in st.index_fallback.items()
                    if c - ifb0.get(r, 0) > 0}
            entry["index"] = {
                "segments": d_segs,
                "device_segments": st.index_device_segments - idev0,
                "fallback": d_fb,
                "terms_scanned": st.index_terms_scanned - iscan0,
                "terms_prefiltered": st.index_terms_prefiltered - ipre0,
                "postings_rows": st.index_postings_rows - irows0,
            }
        # pipelined-dataflow overlap this node's subtree accrued: wall
        # time vs sum-of-stage time per group (storage/pipeline.py) —
        # the per-query proof that gather legs overlapped decode rungs
        pg0, pw0, ps0 = pipe0
        d_groups = st.pipeline_groups - pg0
        if d_groups > 0:
            d_wall = st.pipeline_wall_s - pw0
            d_stage = {k: round((v - ps0.get(k, 0.0)) * 1e3, 3)
                       for k, v in st.pipeline_stage_s.items()
                       if v - ps0.get(k, 0.0) > 0}
            stage_sum = sum(d_stage.values())
            entry["pipeline"] = {
                "groups": d_groups,
                "wall_ms": round(d_wall * 1e3, 3),
                "stage_ms": d_stage,
                "overlap": round(stage_sum / (d_wall * 1e3), 3)
                if d_wall > 0 else 0.0,
            }
        deltas = {
            "series": st.series_matched - series0,
            "blocks": st.blocks_read - blocks0,
            "bytes": st.bytes_decoded - bytes0,
            "cache_hits": st.cache_hits - hits0,
            "cache_misses": st.cache_misses - miss0,
        }
        for k, v in deltas.items():
            if v:
                entry[k] = v
        rungs = {r: c - rungs0.get(r, 0)
                 for r, c in st.decode_rungs.items()
                 if c - rungs0.get(r, 0) > 0}
        if rungs:
            entry["rungs"] = rungs
        # remote legs this node's evaluation added AND no descendant plan
        # node already claimed (children exit first): one child entry per
        # storage node / fanout zone, nested under this plan node like a
        # remote span under its parent
        for leg, (calls, secs, rows) in st.node_legs.items():
            c0, s0, r0 = legs0.get(leg, (0, 0.0, 0))
            cc, cs, cr = self._claimed.get(leg, (0, 0.0, 0))
            cc0, cs0, cr0 = claimed0.get(leg, (0, 0.0, 0))
            n_calls = (calls - c0) - (cc - cc0)
            if n_calls <= 0:
                continue
            child = self._new_entry("rpc", leg)
            child["parent_span_id"] = entry["span_id"]
            child["calls"] = n_calls
            child["duration_ms"] = round(
                ((secs - s0) - (cs - cs0)) * 1e3, 3)
            n_rows = (rows - r0) - (cr - cr0)
            if n_rows:
                child["rows"] = n_rows
            self._claimed[leg] = (cc + n_calls,
                                  cs + (secs - s0) - (cs - cs0),
                                  cr + n_rows)

    def tree(self) -> list[dict]:
        return trace.build_tree(self.entries)

    def set_compiled(self, info: dict) -> None:
        """Record whether the compiled path served this query (the plan-
        cache key and hit/miss ride the ?explain= envelope and the ring)."""
        self.compiled = info

    def add_tier(self, info: dict) -> None:
        """Record one selector fetch's tier-resolution choice (the
        cheapest-tier routing, query/resolver.resolve_read)."""
        self.tiers.append(info)

    def to_dict(self) -> dict:
        doc = {"mode": "analyze" if self.analyze else "plan",
               "tree": self.tree()}
        if self.compiled is not None:
            doc["compiled"] = self.compiled
        if self.tiers:
            doc["tiers"] = self.tiers
        return doc


def remember(record: dict) -> None:
    """Admit one finished EXPLAIN record to the /debug/explain ring."""
    with _ring_lock:
        _ring.append(record)


def recent(limit: int = 20) -> list[dict]:
    """Ring contents, newest first."""
    with _ring_lock:
        entries = list(_ring)
    return entries[::-1][:limit]


def find(trace_id: str) -> list[dict]:
    """Ring records for one trace id (the /debug/traces cross-link)."""
    with _ring_lock:
        return [r for r in _ring if r.get("trace_id") == trace_id]


def clear() -> None:
    with _ring_lock:
        _ring.clear()
