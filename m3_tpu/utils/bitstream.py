"""MSB-first bit streams.

Semantics match the reference OStream/IStream
(/root/reference/src/dbnode/encoding/{ostream,istream}.go): bits are packed
most-significant-first into bytes; WriteBits writes the low `n` bits of the
value, most significant of those first.

This is the host-side (control plane) implementation; the batched TPU
encode/decode kernels in m3_tpu.encoding.m3tsz.tpu operate on whole tensors
of series at once and produce the identical bit layout.
"""

from __future__ import annotations

_MASK64 = (1 << 64) - 1


class OStream:
    """Append-only bit output stream."""

    __slots__ = ("_acc", "_nbits", "_buf")

    def __init__(self) -> None:
        self._buf = bytearray()
        self._acc = 0  # partial byte accumulator (< 8 bits), MSB-aligned int
        self._nbits = 0  # number of valid bits in _acc (0..7)

    def write_bit(self, v: int) -> None:
        self.write_bits(v & 1, 1)

    def write_bits(self, v: int, n: int) -> None:
        if n == 0:
            return
        v &= (1 << n) - 1
        acc = (self._acc << n) | v
        nbits = self._nbits + n
        while nbits >= 8:
            nbits -= 8
            self._buf.append((acc >> nbits) & 0xFF)
        self._acc = acc & ((1 << nbits) - 1)
        self._nbits = nbits

    def write_byte(self, v: int) -> None:
        self.write_bits(v & 0xFF, 8)

    def write_bytes(self, bs: bytes) -> None:
        if self._nbits == 0:
            self._buf.extend(bs)
        else:
            for b in bs:
                self.write_bits(b, 8)

    @property
    def bit_length(self) -> int:
        return len(self._buf) * 8 + self._nbits

    def raw(self) -> tuple[bytes, int]:
        """(complete bytes + possibly-partial last byte, bit pos in last byte).

        pos follows the reference convention: 8 when the last byte is full,
        1..7 when partial (partial bits are MSB-aligned, zero padded).
        """
        if self._nbits == 0:
            return bytes(self._buf), 8 if self._buf else 0
        return bytes(self._buf) + bytes([(self._acc << (8 - self._nbits)) & 0xFF]), self._nbits

    def bytes_padded(self) -> bytes:
        """Stream contents zero-padded to a byte boundary."""
        return self.raw()[0]


class IStream:
    """Bit input stream over bytes."""

    __slots__ = ("_data", "_bitpos", "_nbits")

    def __init__(self, data: bytes) -> None:
        self._data = data
        self._bitpos = 0
        self._nbits = len(data) * 8

    @property
    def remaining_bits(self) -> int:
        return self._nbits - self._bitpos

    def read_bits(self, n: int) -> int:
        v = self.peek_bits(n)
        self._bitpos += n
        return v

    def peek_bits(self, n: int) -> int:
        if self._bitpos + n > self._nbits:
            raise EOFError("bit stream exhausted")
        start = self._bitpos
        end = start + n
        first_byte = start >> 3
        last_byte = (end + 7) >> 3
        chunk = int.from_bytes(self._data[first_byte:last_byte], "big")
        total_bits = (last_byte - first_byte) * 8
        chunk >>= total_bits - (end - first_byte * 8)
        return chunk & ((1 << n) - 1)

    def read_bit(self) -> int:
        return self.read_bits(1)

    def read_byte(self) -> int:
        return self.read_bits(8)

    def read_bytes(self, n: int) -> bytes:
        return bytes(self.read_bits(8) for _ in range(n))


def leading_zeros64(v: int) -> int:
    if v == 0:
        return 64
    return 64 - v.bit_length()


def trailing_zeros64(v: int) -> int:
    if v == 0:
        return 0  # matches reference LeadingAndTrailingZeros(0) = (64, 0)
    return (v & -v).bit_length() - 1


def num_sig(v: int) -> int:
    """Number of significant bits (reference encoding/encoding.go:29)."""
    return v.bit_length()


def sign_extend(v: int, n: int) -> int:
    """Interpret the low n bits of v as an n-bit two's-complement integer."""
    sign_bit = 1 << (n - 1)
    return (v & (sign_bit - 1)) - (v & sign_bit)
