"""Lean binary wire frames for the fat inter-node flows (ROADMAP #1).

Every fat coordinator<->dbnode<->peer flow used to ship float64 JSON:
`/read_batch` repeated every decoded sample as a `[t, v]` text pair,
`/blocks/stream` wrapped the already-compact m3tsz stream in base64 +
JSON, and `/blocks/rollup` base64'd the packed ROLLUP_DTYPE table.  This
module is the shared frame codec that lifts the in-tree codecs onto the
wire instead:

- ``pack_samples``/``unpack_samples`` frame a ragged ``(offsets,
  lengths, samples)`` CSR for the read_batch rows.  The default column
  mode re-encodes the samples with the m3tsz delta-of-delta/XOR codec
  (``encoding/m3tsz/hostpath`` — native + device rungs, exact bit
  round-trip); under the client's negotiated ``?precision=bf16`` grant
  (storage/hottier) the value column rides ``ops/ragged.bf16_pack``
  instead (half the bytes of raw float64, quantized).  The receiver
  lands the CSR directly into ``RaggedSeries`` / the whole-query
  compiler's ``_slab_cuts`` host prep — zero JSON re-assembly.
- ``pack_blobs``/``unpack_blobs`` frame length-prefixed raw byte
  columns for the peer ``stream_block`` and ``rollup`` flows (no
  base64, no JSON envelope).

Negotiation is per connection, Accept/Content-Type style: a capable
client sends ``Accept: application/x-m3wire``; a capable server answers
with that Content-Type and a frame, anything else answers JSON and the
client parses it transparently (``count_fallback`` keeps the ledger —
mixed-version fleets degrade to JSON, never to an error).  The
``M3_TPU_WIRE=json`` hatch pins either side back to the legacy JSON
wire byte-identically.

Frame codec idiom (the PR-9 ``peers.ROLLUP_DTYPE`` template, pinned by
m3lint ``inv-wire-frame-scope``): every struct/dtype below is built
ONCE at module scope, never per request.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

# the negotiated binary media type (Accept on requests, Content-Type on
# framed responses); anything else on the wire is the legacy JSON plane
CONTENT_TYPE = "application/x-m3wire"

MAGIC = b"M3WF"
VERSION = 1

# frame kinds
KIND_SAMPLES = 1   # read_batch rows: ragged CSR sample columns
KIND_BLOCK = 2     # peer stream_block: [m3tsz stream, encoded tags]
KIND_ROLLUP = 3    # peer rollup digests: [packed ROLLUP_DTYPE table]

# sample column modes (KIND_SAMPLES)
MODE_F64 = 0       # exact: raw <i8 times + <u8 value-bit columns
MODE_M3TSZ = 1     # exact: per-row m3tsz delta-of-delta/XOR streams
MODE_BF16 = 2      # quantized: raw <i8 times + bf16 <u2 value column
                   # (only under the explicit ?precision=bf16 grant)

# module-scope codec objects — the whole point of the frame idiom: one
# header Struct and one dtype per column for the life of the process
_HEADER = struct.Struct("<4sBBBxI")   # magic, version, kind, mode, n_rows
_U32 = np.dtype("<u4")                # per-row lengths column
_I64 = np.dtype("<i8")                # timestamp column
_U64 = np.dtype("<u8")                # float64 value-bit column
_U16 = np.dtype("<u2")                # bf16 value column


class WireError(ValueError):
    """A frame that does not parse (bad magic/version/length)."""


def wire_mode() -> str:
    """The M3_TPU_WIRE hatch: 'packed' (default) arms the binary frames,
    'json' pins this side to the legacy JSON wire byte-identically."""
    return "json" if os.environ.get("M3_TPU_WIRE", "").strip().lower() \
        == "json" else "packed"


def packed_enabled() -> bool:
    return wire_mode() == "packed"


def accepts_packed(headers) -> bool:
    """Server-side capability probe: did the client's Accept header
    offer the binary media type? (dict or http.server Message, absent on
    legacy/mixed-version clients)."""
    if headers is None:
        return False
    try:
        accept = headers.get("Accept") or ""
    except AttributeError:
        return False
    return CONTENT_TYPE in accept


def is_packed(ctype: str | None) -> bool:
    """Client-side: did the server answer with a binary frame?"""
    return bool(ctype) and ctype.split(";")[0].strip() == CONTENT_TYPE


# ---------------------------------------------------------------------------
# per-flow wire accounting + the counted JSON fallback
# ---------------------------------------------------------------------------


_byte_scopes: dict = {}
_fallback_scopes: dict = {}


def account(flow: str, *, sent: int = 0, recv: int = 0) -> None:
    """net_bytes_{sent,recv}{flow=} — the bytes-on-wire ledger, counted
    by the CLIENT side of each flow (one unambiguous owner per counter:
    the coordinator accounts read_batch + response, a repairing dbnode
    accounts stream_block + rollup)."""
    sc = _byte_scopes.get(flow)
    if sc is None:
        from m3_tpu.utils.instrument import default_registry

        sc = default_registry().root_scope("net").subscope("bytes",
                                                           flow=flow)
        _byte_scopes[flow] = sc
    if sent:
        sc.counter("sent", sent)
    if recv:
        sc.counter("recv", recv)


def count_fallback(reason: str) -> None:
    """wire.fallback{reason=} tracepoint + counter: a packed-capable
    side served/parsed legacy JSON instead (mixed-version fleet, or a
    payload the frame codec declined).  Counted, never an error."""
    from m3_tpu.utils import trace

    sc = _fallback_scopes.get(reason)
    if sc is None:
        from m3_tpu.utils.instrument import default_registry

        sc = default_registry().root_scope("net").subscope("wire",
                                                           reason=reason)
        _fallback_scopes[reason] = sc
    sc.counter("fallback")
    with trace.span(trace.WIRE_FALLBACK, reason=reason):
        pass


# ---------------------------------------------------------------------------
# KIND_SAMPLES: the ragged CSR sample frame (read_batch rows)
# ---------------------------------------------------------------------------


def _pack_frame(kind: int, mode: int, n_rows: int, stats: dict | None,
                columns: list[bytes]) -> bytes:
    stats_blob = json.dumps(stats).encode() if stats else b""
    parts = [_HEADER.pack(MAGIC, VERSION, kind, mode, n_rows),
             struct.pack("<I", len(stats_blob)), stats_blob]
    parts.extend(columns)
    return b"".join(parts)


def _unpack_frame(buf: bytes):
    """(kind, mode, n_rows, stats, body) — shared header/stats parse."""
    if len(buf) < _HEADER.size + 4:
        raise WireError(f"frame too short: {len(buf)} bytes")
    magic, version, kind, mode, n_rows = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {magic!r}")
    if version != VERSION:
        raise WireError(f"unsupported frame version {version}")
    off = _HEADER.size
    (stats_len,) = struct.unpack_from("<I", buf, off)
    off += 4
    if off + stats_len > len(buf):
        raise WireError("stats blob overruns frame")
    stats = json.loads(buf[off:off + stats_len]) if stats_len else None
    return kind, mode, n_rows, stats, memoryview(buf)[off + stats_len:]


def _column(body: memoryview, off: int, dtype: np.dtype, count: int):
    """One fixed-width column copied out of the frame (writable — the
    CSR lands in merge/sort paths that mutate)."""
    nbytes = count * dtype.itemsize
    if off + nbytes > len(body):
        raise WireError("column overruns frame")
    arr = np.frombuffer(body, dtype=dtype, count=count, offset=off).copy()
    return arr, off + nbytes


def pack_samples(times: np.ndarray, vbits: np.ndarray, offsets: np.ndarray,
                 *, precision: str | None = None,
                 stats: dict | None = None) -> bytes:
    """Frame a ragged CSR of samples for the wire.

    Default mode is the exact m3tsz re-encode (per-row delta-of-delta/
    XOR streams at nanosecond unit — bit-exact round trip, typically a
    small fraction of the raw column bytes).  ``precision='bf16'``
    (the negotiated per-query grant) quantizes the VALUE column to bf16
    instead; timestamps always stay exact.  A CSR the block codec
    declines (encode overflow) degrades to the raw float64 columns —
    still framed, still exact, never JSON."""
    offsets = np.ascontiguousarray(offsets, _I64)
    times = np.ascontiguousarray(times, _I64)
    vbits = np.ascontiguousarray(np.asarray(vbits).view(np.uint64), _U64)
    n_rows = len(offsets) - 1
    lens = np.diff(offsets)
    if precision == "bf16":
        from m3_tpu.ops import ragged

        packed = ragged.bf16_pack(vbits.view(np.float64))
        cols = [lens.astype(_U32).tobytes(), times.tobytes(),
                np.ascontiguousarray(packed, _U16).tobytes()]
        return _pack_frame(KIND_SAMPLES, MODE_BF16, n_rows, stats, cols)
    try:
        streams = _encode_rows(times, vbits, offsets)
    except (OverflowError, ValueError):
        streams = None
    if streams is None or sum(map(len, streams)) >= times.nbytes \
            + vbits.nbytes:
        # encode declined, or the samples are incompressible (random
        # bits XOR to full width): raw columns are exact AND smaller
        cols = [lens.astype(_U32).tobytes(), times.tobytes(),
                vbits.tobytes()]
        return _pack_frame(KIND_SAMPLES, MODE_F64, n_rows, stats, cols)
    stream_lens = np.fromiter((len(s) for s in streams), np.int64, n_rows)
    cols = [stream_lens.astype(_U32).tobytes()]
    cols.extend(streams)
    return _pack_frame(KIND_SAMPLES, MODE_M3TSZ, n_rows, stats, cols)


def _encode_rows(times, vbits, offsets) -> list[bytes]:
    """Per-row m3tsz streams for a CSR: each row's block start is its
    own first timestamp (the encoder writes the first time as raw 64-bit
    nanos, so arbitrary starts round-trip exactly at ns unit); empty
    rows frame as zero-length streams."""
    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.utils.xtime import TimeUnit

    n_rows = len(offsets) - 1
    starts = np.zeros(n_rows, np.int64)
    nonempty = np.diff(offsets) > 0
    if nonempty.any():
        starts[nonempty] = times[offsets[:-1][nonempty]]
    return hostpath.encode_blocks_ragged(times, vbits, offsets, starts,
                                         TimeUnit.NANOSECOND, False,
                                         waste_site="wire_encode")


def unpack_samples(buf: bytes):
    """(times int64, vbits uint64, offsets int64, stats dict | None)
    from a KIND_SAMPLES frame — the CSR the receiver hands straight to
    RaggedSeries / the compiler's slab prep."""
    kind, mode, n_rows, stats, body = _unpack_frame(buf)
    if kind != KIND_SAMPLES:
        raise WireError(f"expected samples frame, got kind {kind}")
    lens32, off = _column(body, 0, _U32, n_rows)
    if mode == MODE_M3TSZ:
        from m3_tpu.encoding.m3tsz import hostpath
        from m3_tpu.ops import ragged
        from m3_tpu.utils.xtime import TimeUnit

        streams = []
        for n in lens32.astype(np.int64).tolist():
            if off + n > len(body):
                raise WireError("stream column overruns frame")
            streams.append(bytes(body[off:off + n]))
            off += n
        pairs = hostpath.decode_streams_batch(streams, TimeUnit.NANOSECOND,
                                              False)
        times, vbits, offsets = ragged.pairs_to_csr(pairs)
        return times, vbits, offsets, stats
    counts = lens32.astype(np.int64)
    offsets = np.zeros(n_rows + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    n = int(offsets[-1])
    times, off = _column(body, off, _I64, n)
    if mode == MODE_F64:
        vbits, off = _column(body, off, _U64, n)
    elif mode == MODE_BF16:
        from m3_tpu.ops import ragged

        packed, off = _column(body, off, _U16, n)
        vbits = ragged.bf16_unpack(packed).view(np.uint64)
    else:
        raise WireError(f"unknown sample column mode {mode}")
    return times.astype(np.int64, copy=False), vbits, offsets, stats


# ---------------------------------------------------------------------------
# KIND_BLOCK / KIND_ROLLUP: length-prefixed raw byte columns
# ---------------------------------------------------------------------------


def pack_blobs(kind: int, blobs: list[bytes]) -> bytes:
    """Frame raw byte strings (an m3tsz block stream + its encoded tags,
    a packed rollup table) without base64 or a JSON envelope."""
    lens = np.fromiter((len(b) for b in blobs), np.int64, len(blobs))
    cols = [lens.astype(_U32).tobytes()]
    cols.extend(blobs)
    return _pack_frame(kind, 0, len(blobs), None, cols)


def unpack_blobs(buf: bytes, kind: int) -> list[bytes]:
    got_kind, _mode, n_rows, _stats, body = _unpack_frame(buf)
    if got_kind != kind:
        raise WireError(f"expected kind {kind} frame, got {got_kind}")
    lens32, off = _column(body, 0, _U32, n_rows)
    out = []
    for n in lens32.astype(np.int64).tolist():
        if off + n > len(body):
            raise WireError("blob column overruns frame")
        out.append(bytes(body[off:off + n]))
        off += n
    return out
