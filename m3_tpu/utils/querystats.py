"""Per-query statistics + the slow-query log.

Role parity with the reference's query diagnostics (per-fetch result
metadata + slow-query logging in the coordinator): a `QueryStats` record
rides a thread-local through the engine -> resolver -> storage -> decode
call stack, so every layer can account what THIS query cost without
threading a parameter through a dozen signatures:

- the engine opens/finishes the record (query text, namespace, trace id,
  total duration) and exposes it per thread as `Engine.last_stats`;
- the resolver records series matched and per-stage durations
  (query_ids / read_many);
- the block cache records hits/misses, the decode ladder records which
  rung served each (shard, block, volume) group and the bytes decoded.

Finished records land in a bounded ring served at /debug/slow_queries.
Admission is PERCENTILE-BASED when a request-latency histogram source is
registered (the coordinator registers its `request_seconds` histogram):
a query is slow when it exceeds the live p99 of that histogram —
`M3_TPU_SLOW_QUERY_MS` is the FLOOR under the adaptive bar, and the
fallback threshold while the histogram is still too thin to trust
(default 0: every query is kept — the ring IS the query log until the
p99 bar arms or an operator raises the floor). The HTTP layer embeds
the record in the response envelope under `stats`.

In cluster mode the storage/decode counters accrue on the STORAGE node
processes (their own /metrics histograms cover them); the coordinator's
record still carries matching, stage timing and duration.

Overhead when no query is active: each hook is one thread-local read.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class QueryStats:
    query: str = ""
    namespace: str = ""
    start_unix_ns: int = 0
    trace_id: str = ""
    series_matched: int = 0
    blocks_read: int = 0
    bytes_decoded: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    # decode rung -> groups served (device / native / scalar / cache)
    decode_rungs: dict = field(default_factory=dict)
    # stage name -> seconds (query_ids, read_many, eval)
    stages: dict = field(default_factory=dict)
    # remote leg -> (calls, seconds, rows): one entry per storage node /
    # fanout zone this query touched (the cross-node half of EXPLAIN
    # ANALYZE — the coordinator's plan tree shows each node's share)
    node_legs: dict = field(default_factory=dict)
    # pipelined-dataflow overlap (storage/pipeline.py run_stages): how
    # many (shard, block) groups rode the executor, the wall time of the
    # pipelined pass, and the per-stage (gather/decode) time sums —
    # stage_sum > wall is overlap, surfaced on ?explain=analyze
    pipeline_groups: int = 0
    pipeline_wall_s: float = 0.0
    pipeline_stage_s: dict = field(default_factory=dict)
    # device-compiled inverted index (index/device.py): segments the
    # postings walk visited, how many ran the fused device program vs
    # fell back to the scalar walk (and why), the term-dictionary scan
    # account (terms regex-scanned vs skipped by literal prefix/suffix
    # narrowing) and postings rows fed to the intersect legs — the
    # ?explain=analyze `index` block
    index_segments: int = 0
    index_device_segments: int = 0
    index_fallback: dict = field(default_factory=dict)  # reason -> segments
    index_terms_scanned: int = 0
    index_terms_prefiltered: int = 0
    index_postings_rows: int = 0
    duration_s: float = 0.0

    def to_dict(self) -> dict:
        out = {
            "query": self.query,
            "namespace": self.namespace,
            "start_unix_ns": self.start_unix_ns,
            "trace_id": self.trace_id,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "series_matched": self.series_matched,
            "blocks_read": self.blocks_read,
            "bytes_decoded": self.bytes_decoded,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "decode_rungs": dict(self.decode_rungs),
            "stages_ms": {k: round(v * 1e3, 3) for k, v in self.stages.items()},
        }
        if self.node_legs:
            out["node_legs"] = {
                host: {"calls": c, "ms": round(s * 1e3, 3), "rows": r}
                for host, (c, s, r) in self.node_legs.items()
            }
        if self.index_segments:
            out["index"] = self.index_block()
        if self.pipeline_groups:
            stage_sum = sum(self.pipeline_stage_s.values())
            out["pipeline"] = {
                "groups": self.pipeline_groups,
                "wall_ms": round(self.pipeline_wall_s * 1e3, 3),
                "stage_ms": {k: round(v * 1e3, 3)
                             for k, v in self.pipeline_stage_s.items()},
                "stage_sum_ms": round(stage_sum * 1e3, 3),
                # >1.0 means stages overlapped in wall time
                "overlap": round(stage_sum / self.pipeline_wall_s, 3)
                if self.pipeline_wall_s > 0 else 0.0,
            }
        return out

    def index_block(self) -> dict:
        """The rendered ?explain=analyze / stats-envelope `index` doc."""
        return {
            "segments": self.index_segments,
            "device_segments": self.index_device_segments,
            "fallback": dict(self.index_fallback),
            "terms_scanned": self.index_terms_scanned,
            "terms_prefiltered": self.index_terms_prefiltered,
            "postings_rows": self.index_postings_rows,
        }


_tls = threading.local()
_ring_lock = threading.Lock()
_ring: deque[QueryStats] = deque(maxlen=256)

# the slow-query ring is a bounded buffer: its fill level rides the
# saturation plane (instrument.monitor_queue; m3lint inv-queue-gauge)
from m3_tpu.utils import instrument as _instrument  # noqa: E402

_instrument.monitor_queue("slow_query_ring", lambda: len(_ring),
                          _ring.maxlen)


def _env_threshold_s() -> float:
    try:
        return float(os.environ.get("M3_TPU_SLOW_QUERY_MS", "0")) / 1e3
    except ValueError:
        return 0.0


_threshold_s = _env_threshold_s()
# adaptive slow-query bar: (histogram_source_fn, quantile, min_count).
# The coordinator registers its request-latency histogram; while the
# histogram holds fewer than min_count observations the env/floor
# threshold governs alone (a 3-sample p99 is noise, not a bar).
_adaptive: tuple | None = None


def set_threshold_ms(ms: float) -> None:
    """FLOOR threshold for the slow-query ring (0 keeps everything until
    the adaptive p99 bar arms)."""
    global _threshold_s
    _threshold_s = max(0.0, float(ms)) / 1e3


def set_adaptive_source(source, quantile: float = 0.99,
                        min_count: int = 64) -> None:
    """Register (or clear, with None) the live histogram the slow-query
    bar derives from: `source()` returns an object with `.count` and
    `.quantile(q)` (utils/instrument._Histogram). Admission threshold
    becomes max(floor, histogram p99) once the histogram holds min_count
    observations."""
    global _adaptive
    _adaptive = None if source is None else (source, quantile, min_count)


def clear_adaptive_source(source) -> None:
    """Clear the adaptive bar ONLY if `source` is the currently
    registered one — a shutting-down CoordinatorAPI must not disarm a
    sibling instance's registration (the bar is process-global)."""
    global _adaptive
    if _adaptive is not None and _adaptive[0] is source:
        _adaptive = None


def threshold_s() -> float:
    """The CURRENT admission bar: the env/operator floor, raised to the
    registered histogram's live quantile once it has enough samples."""
    thr = _threshold_s
    if _adaptive is not None:
        source, q, min_count = _adaptive
        try:
            h = source()
            if h is not None and h.count >= min_count:
                p = h.quantile(q)
                if p == p:  # not NaN
                    thr = max(thr, p)
        except Exception:  # noqa: BLE001 - a broken source must never
            pass           # make finish() raise
    return thr


def current() -> QueryStats | None:
    return getattr(_tls, "current", None)


def start(query: str = "", namespace: str = "",
          clock=None) -> QueryStats:
    """Open a record for this thread's query. Nested engines (subqueries,
    front-ends compiling through the same engine) keep the OUTER record:
    the inner call gets the same object back with a depth mark, and only
    the matching outermost `finish` closes it. `clock` (seconds, default
    perf_counter) is injectable so admission tests run on virtual time."""
    cur = getattr(_tls, "current", None)
    if cur is not None:
        cur._depth = getattr(cur, "_depth", 0) + 1  # type: ignore[attr-defined]
        return cur
    st = QueryStats(query=query, namespace=namespace,
                    start_unix_ns=time.time_ns())
    st._clock = clock or time.perf_counter  # type: ignore[attr-defined]
    st._t0 = st._clock()  # type: ignore[attr-defined]
    st._depth = 0  # type: ignore[attr-defined]
    _tls.current = st
    return st


def finish(st: QueryStats) -> None:
    """Close the record, stamp duration, admit to the ring when it clears
    the threshold bar (env floor raised to the live p99 once the adaptive
    source arms). A nested finish (depth > 0) only pops one level — the
    outer query keeps accruing; object identity alone can't tell owner
    from nested caller since start() hands the same record back."""
    if getattr(_tls, "current", None) is not st:
        return
    depth = getattr(st, "_depth", 0)
    if depth > 0:
        st._depth = depth - 1  # type: ignore[attr-defined]
        return
    _tls.current = None
    clock = getattr(st, "_clock", time.perf_counter)
    st.duration_s = clock() - getattr(st, "_t0", clock())
    if st.duration_s >= threshold_s():
        with _ring_lock:
            _ring.append(st)


def record(series_matched: int = 0, blocks_read: int = 0,
           bytes_decoded: int = 0, cache_hits: int = 0,
           cache_misses: int = 0, decode_rung: str | None = None) -> None:
    """Accrue deltas onto the active query's record (no-op outside one)."""
    st = getattr(_tls, "current", None)
    if st is None:
        return
    st.series_matched += series_matched
    st.blocks_read += blocks_read
    st.bytes_decoded += bytes_decoded
    st.cache_hits += cache_hits
    st.cache_misses += cache_misses
    if decode_rung is not None:
        st.decode_rungs[decode_rung] = st.decode_rungs.get(decode_rung, 0) + 1


def record_pipeline(groups: int, wall_s: float, stages: dict) -> None:
    """Accrue one pipelined-dataflow pass (storage/pipeline run_stages)
    onto the active query's record: groups scheduled, wall time, and
    per-stage time sums. ?explain=analyze renders wall vs stage-sum so
    the gather/decode overlap is visible per query. No-op outside one."""
    st = getattr(_tls, "current", None)
    if st is None or not groups:
        return
    st.pipeline_groups += groups
    st.pipeline_wall_s += wall_s
    for stage, dt in stages.items():
        st.pipeline_stage_s[stage] = st.pipeline_stage_s.get(stage, 0.0) + dt


def record_index(segments: int = 0, device_segments: int = 0,
                 fallback: str | None = None, terms_scanned: int = 0,
                 terms_prefiltered: int = 0,
                 postings_rows: int = 0) -> None:
    """Accrue one postings-walk account (index/executor.py) onto the
    active query's record: segments visited, device-program vs
    scalar-fallback outcomes (with the fallback reason), the term
    dictionary scan/prefilter split and postings rows intersected — the
    ?explain=analyze `index` block. No-op outside a query."""
    st = getattr(_tls, "current", None)
    if st is None:
        return
    st.index_segments += segments
    st.index_device_segments += device_segments
    if fallback is not None:
        st.index_fallback[fallback] = st.index_fallback.get(fallback, 0) + 1
    st.index_terms_scanned += terms_scanned
    st.index_terms_prefiltered += terms_prefiltered
    st.index_postings_rows += postings_rows


def record_node_leg(leg: str, seconds: float, rows: int = 0) -> None:
    """Accrue one remote leg (storage-node RPC, fanout zone) onto the
    active query's record: EXPLAIN ANALYZE shows each node's share of a
    fan-out stage. No-op outside a query."""
    st = getattr(_tls, "current", None)
    if st is None:
        return
    calls, total_s, total_rows = st.node_legs.get(leg, (0, 0.0, 0))
    st.node_legs[leg] = (calls + 1, total_s + seconds, total_rows + rows)


@contextmanager
def collect():
    """Scoped storage-counter collection WITHOUT slow-query-ring
    admission: the node half of the /read_batch stats envelope. Pushes a
    fresh record (shadowing any active one) so the yielded counters
    cover exactly the wrapped work; the previous record is restored on
    exit, unchanged — whoever reads the envelope decides to merge."""
    prev = getattr(_tls, "current", None)
    st = QueryStats()
    _tls.current = st
    try:
        yield st
    finally:
        _tls.current = prev


def storage_counters(st: QueryStats) -> dict:
    """The storage-side counters a node embeds in its /read_batch
    response envelope (merged coordinator-side via merge_storage)."""
    out = {"series": st.series_matched, "blocks": st.blocks_read,
           "bytes": st.bytes_decoded, "cache_hits": st.cache_hits,
           "cache_misses": st.cache_misses, "rungs": dict(st.decode_rungs)}
    if st.index_segments:
        out["index"] = st.index_block()
    if st.pipeline_groups:
        out["pipeline"] = {"groups": st.pipeline_groups,
                           "wall_s": st.pipeline_wall_s,
                           "stages": dict(st.pipeline_stage_s)}
    return out


def merge_storage(doc: dict | None) -> None:
    """Accrue a node's returned storage counters onto this thread's
    active record (the coordinator half; no-op outside a query) — so in
    cluster mode /debug/slow_queries and the response `stats` envelope
    carry the nodes' blocks/bytes/cache/rung counts, not zeros."""
    st = getattr(_tls, "current", None)
    if st is None or not doc:
        return
    st.series_matched += int(doc.get("series", 0))
    st.blocks_read += int(doc.get("blocks", 0))
    st.bytes_decoded += int(doc.get("bytes", 0))
    st.cache_hits += int(doc.get("cache_hits", 0))
    st.cache_misses += int(doc.get("cache_misses", 0))
    for rung, cnt in (doc.get("rungs") or {}).items():
        st.decode_rungs[rung] = st.decode_rungs.get(rung, 0) + int(cnt)
    idx = doc.get("index")
    if idx:
        st.index_segments += int(idx.get("segments", 0))
        st.index_device_segments += int(idx.get("device_segments", 0))
        for reason, cnt in (idx.get("fallback") or {}).items():
            st.index_fallback[reason] = \
                st.index_fallback.get(reason, 0) + int(cnt)
        st.index_terms_scanned += int(idx.get("terms_scanned", 0))
        st.index_terms_prefiltered += int(idx.get("terms_prefiltered", 0))
        st.index_postings_rows += int(idx.get("postings_rows", 0))
    pipe = doc.get("pipeline")
    if pipe:
        record_pipeline(int(pipe.get("groups", 0)),
                        float(pipe.get("wall_s", 0.0)),
                        {k: float(v)
                         for k, v in (pipe.get("stages") or {}).items()})


@contextmanager
def stage(name: str):
    """Time a named stage of the active query (no-op outside one)."""
    st = getattr(_tls, "current", None)
    if st is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        st.stages[name] = st.stages.get(name, 0.0) + time.perf_counter() - t0


def slow_queries(limit: int = 50) -> list[dict]:
    """Ring contents, slowest first."""
    with _ring_lock:
        entries = list(_ring)
    entries.sort(key=lambda s: s.duration_s, reverse=True)
    return [s.to_dict() for s in entries[:limit]]


def clear() -> None:
    with _ring_lock:
        _ring.clear()
