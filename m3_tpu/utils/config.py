"""Configuration loader: YAML-subset files with env-var expansion.

Role parity with the reference config system
(/root/reference/src/x/config/config.go:73-93 — YAML + ${ENV:default}
expansion + validation). To stay dependency-free this parses the YAML
subset real deployments use (nested mappings, lists of scalars/mappings,
scalars with comments); anchors/multiline scalars are out of scope.
"""

from __future__ import annotations

import os
import re
from typing import Any

_ENV_RE = re.compile(r"\$\{(\w+)(?::([^}]*))?\}")


def expand_env(text: str, env: dict | None = None) -> str:
    env = env if env is not None else os.environ

    def sub(m: re.Match) -> str:
        name, default = m.group(1), m.group(2)
        val = env.get(name)
        if val is None:
            if default is None:
                raise KeyError(f"environment variable {name} not set and no default")
            return default
        return val

    return _ENV_RE.sub(sub, text)


def _parse_scalar(s: str) -> Any:
    s = s.strip()
    if s in ("null", "~", ""):
        return None
    if s in ("true", "True"):
        return True
    if s in ("false", "False"):
        return False
    if s.startswith('"') and s.endswith('"') or s.startswith("'") and s.endswith("'"):
        return s[1:-1]
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    return s


def _strip_comment(line: str) -> str:
    out = []
    in_s = in_d = False
    for ch in line:
        if ch == "'" and not in_d:
            in_s = not in_s
        elif ch == '"' and not in_s:
            in_d = not in_d
        elif ch == "#" and not in_s and not in_d:
            break
        out.append(ch)
    return "".join(out).rstrip()


def parse_yaml(text: str) -> Any:
    """Parse the YAML subset (nested maps, lists, scalars)."""
    lines = []
    for raw in text.splitlines():
        line = _strip_comment(raw)
        if line.strip():
            lines.append(line)
    value, rest = _parse_block(lines, 0, _indent(lines[0]) if lines else 0)
    if rest:
        raise ValueError(f"trailing unparsed config lines: {rest[:2]}")
    return value


def _indent(line: str) -> int:
    return len(line) - len(line.lstrip())


def _parse_block(lines: list[str], pos: int, indent: int):
    if pos >= len(lines):
        return None, []
    if lines[pos].lstrip().startswith("- "):
        return _parse_list(lines, pos, indent)
    return _parse_map(lines, pos, indent)


def _parse_map(lines: list[str], pos: int, indent: int):
    out: dict[str, Any] = {}
    while pos < len(lines):
        line = lines[pos]
        ind = _indent(line)
        if ind < indent:
            break
        if ind > indent:
            raise ValueError(f"bad indent at: {line!r}")
        stripped = line.strip()
        if ":" not in stripped:
            raise ValueError(f"expected key: value, got {stripped!r}")
        key, _, rest = stripped.partition(":")
        key = _parse_scalar(key)
        rest = rest.strip()
        pos += 1
        if rest:
            out[key] = _parse_scalar(rest)
        else:
            deeper = pos < len(lines) and _indent(lines[pos]) > indent
            # standard YAML also allows the list at the SAME indent as its key
            same_list = (
                pos < len(lines)
                and _indent(lines[pos]) == indent
                and lines[pos].lstrip().startswith("- ")
            )
            if deeper or same_list:
                child_indent = _indent(lines[pos])
                child, remaining = _parse_block(lines[pos:], 0, child_indent)
                consumed = len(lines[pos:]) - len(remaining)
                pos += consumed
                out[key] = child
            else:
                out[key] = None
    return out, lines[pos:]


def _parse_list(lines: list[str], pos: int, indent: int):
    out: list[Any] = []
    while pos < len(lines):
        line = lines[pos]
        ind = _indent(line)
        if ind < indent or not line.lstrip().startswith("- "):
            break
        item = line.strip()[2:]
        pos += 1
        # YAML: '- key: value' (space after colon, or trailing colon) starts
        # a mapping; '- 10s:2d' (no space) is a scalar
        if re.match(r"^[^:\s]+:(\s|$)", item):
            sub_lines = [" " * (ind + 2) + item]
            while pos < len(lines) and _indent(lines[pos]) > ind:
                sub_lines.append(lines[pos])
                pos += 1
            child, _ = _parse_map(sub_lines, 0, ind + 2)
            out.append(child)
        else:
            out.append(_parse_scalar(item))
    return out, lines[pos:]


def load_config(path: str, env: dict | None = None) -> Any:
    with open(path) as f:
        raw = f.read()
    # strip comments BEFORE env expansion so a commented-out ${VAR} with no
    # default can't fail the load
    stripped = "\n".join(_strip_comment(line) for line in raw.splitlines())
    return parse_yaml(expand_env(stripped, env))
