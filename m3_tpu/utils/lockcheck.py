"""Runtime shadow-lock checker: dynamic lock-order cycle detection.

The static side (tools/m3lint ``lock-order``) proves intra-module
discipline; this module covers the residue statics can't see — locks
handed across modules, orders that only materialize on real thread
interleavings.  Role parity with Go's ``go test -race`` lock-annotation
half (SURVEY §5), same spirit as pytest running under a deadlock
sentinel.

``M3_TPU_LOCK_CHECK=1`` (read at ``m3_tpu`` import) swaps
``threading.Lock``/``threading.RLock`` for instrumented wrappers that
record, per thread, the set of shadow-locks held at every acquisition
and feed the (held → acquiring) edges into one global order graph.  A
new edge that closes a cycle is a potential deadlock: two threads
driving the two ends of the cycle park forever, no timeout, no stack
trace.  Reports carry both edges' acquisition sites (file:line of the
lock's construction), so the fix is a grep away.

Granularity is the lock's CONSTRUCTION SITE, not the instance — kernel
lockdep's "lock class" semantics.  Every ``Shard._lock`` is one node no
matter how many shards exist, so an order violated between two different
shard instances is still a cycle.  Ordering WITHIN a class (two locks
born on the same source line, e.g. a stripe array) cannot be graph-
validated — nesting two non-reentrant same-class locks is therefore
reported directly, once per class, instead of silently dropped.

Modes:

* ``M3_TPU_LOCK_CHECK=1``      record + report (stderr, once per cycle);
                               ``reports()`` returns them for tests
* ``M3_TPU_LOCK_CHECK=raise``  raise ``LockOrderError`` at the closing
                               acquisition — for tests that PIN ordering

Overhead when disabled: zero — ``install()`` is never called and the
stdlib classes are untouched.  When enabled, each acquire/release pays
one thread-local list op plus, on a NEW edge only, one graph probe under
a private registry lock (steady state adds no registry contention).
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


class LockOrderError(RuntimeError):
    """A lock acquisition closed an ordering cycle (potential deadlock)."""


def env_enabled(value: str | None) -> bool:
    """Is this M3_TPU_LOCK_CHECK value an ENABLE?  '0'/'false'/'off'/'no'
    and empty mean off — the repo's env-flag convention (M3_TPU_NATIVE_OPS=0
    etc.), so an operator disabling the checker gets what they asked for."""
    if value is None:
        return False
    return value.strip().lower() not in ("", "0", "false", "off", "no")


def raise_mode() -> bool:
    """Is M3_TPU_LOCK_CHECK currently asking for raise mode?  Normalized
    the same way env_enabled is — 'RAISE' or ' raise ' must not install
    the checker and then silently degrade to report-only."""
    v = os.environ.get("M3_TPU_LOCK_CHECK")
    return v is not None and v.strip().lower() == "raise"


@dataclass
class CycleReport:
    cycle: tuple[str, ...]          # lock site names along the cycle
    closing_edge: tuple[str, str]   # (held, acquiring) that closed it
    thread: str

    def render(self) -> str:
        path = " -> ".join(self.cycle + (self.cycle[0],))
        return (f"lockcheck: ordering cycle {path} closed by thread "
                f"{self.thread} acquiring {self.closing_edge[1]} while "
                f"holding {self.closing_edge[0]} — two threads entering "
                f"from both ends deadlock")


class _Registry:
    """The global order graph: nodes are lock construction sites, edges
    are observed (held -> acquiring) pairs across all threads."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._edges: dict[str, set[str]] = {}
        self._seen_edges: set[tuple[str, str]] = set()
        self._same_class_seen: set[str] = set()
        self._reports: list[CycleReport] = []
        self._tls = threading.local()

    # -- per-thread held stack --
    def _held(self) -> list:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    def note_acquire(self, lock: "_CheckedLockBase",
                     blocking: bool = True, bounded: bool = False) -> None:
        held = self._held()
        if any(h is lock for h in held):
            if not lock.reentrant and blocking and not bounded:
                # UNBOUNDED same-thread re-acquire of a plain Lock: a
                # GUARANTEED self-deadlock — report before we park
                # forever (the static check only sees intra-module
                # re-acquisition; this is the cross-module residue).
                # Non-blocking probes are exempt: Condition._is_owned
                # legitimately tests ownership via acquire(False), and
                # flagging it would also recurse through _DummyThread
                # creation inside current_thread().  Timeout-bounded
                # acquires are exempt too — a bounded probe simply
                # returns False; calling it a guaranteed deadlock (and
                # raising in raise mode) would be a lie.
                rep = CycleReport(
                    cycle=(lock.site,), closing_edge=(lock.site, lock.site),
                    thread=threading.current_thread().name)
                with self._mu:
                    self._reports.append(rep)
                print(f"lockcheck: non-reentrant lock {lock.site} "
                      f"re-acquired by thread {rep.thread} while already "
                      f"held — self-deadlock", file=sys.stderr)
                if raise_mode():
                    raise LockOrderError(
                        f"self-deadlock: non-reentrant {lock.site} "
                        f"re-acquired while held")
            # reentrant re-acquire: no new ordering information
            held.append(lock)
            return
        # two DIFFERENT instances from the same class (same construction
        # line — striped locks, comprehensions): ordering inside a class
        # cannot be validated by the graph (the edge would be a self
        # loop), so silently dropping it would leave a same-line ABBA
        # deadlock invisible. Lockdep semantics: report the nesting
        # itself, once per class. Report-only — a consistently-ordered
        # stripe sweep is legitimate and indistinguishable without
        # nesting annotations, so raise mode does not abort on it.
        if blocking and not lock.reentrant:
            for h in held:
                if h.site == lock.site and not h.reentrant:
                    rep = CycleReport(
                        cycle=(lock.site,),
                        closing_edge=(lock.site, lock.site),
                        thread=threading.current_thread().name)
                    with self._mu:
                        if lock.site in self._same_class_seen:
                            break
                        self._same_class_seen.add(lock.site)
                        self._reports.append(rep)
                    print(f"lockcheck: nested acquisition of two locks "
                          f"from the same class {lock.site} by thread "
                          f"{rep.thread} — ordering within a lock class "
                          f"is unverifiable; an inconsistently-ordered "
                          f"pair deadlocks", file=sys.stderr)
                    break
        # trylocks contribute NO ordering edges (lockdep semantics): an
        # acquire that cannot block cannot complete a deadlock, so a
        # cycle through it is a false report
        new_edges = [] if not blocking else \
            [(h.site, lock.site) for h in held
             if h.site != lock.site
             and (h.site, lock.site) not in self._seen_edges]
        if new_edges:
            # probe BEFORE pushing onto the held stack: raise-mode must
            # abort the acquisition with the stack still consistent, and
            # a real deadlock must have printed its report before we park
            with self._mu:
                for edge in new_edges:
                    if edge in self._seen_edges:
                        continue
                    self._seen_edges.add(edge)
                    self._edges.setdefault(edge[0], set()).add(edge[1])
                    cycle = self._find_cycle(edge[1], edge[0])
                    if cycle is not None:
                        rep = CycleReport(
                            cycle=tuple(cycle), closing_edge=edge,
                            thread=threading.current_thread().name)
                        self._reports.append(rep)
                        print(rep.render(), file=sys.stderr)
                        if raise_mode():
                            raise LockOrderError(rep.render())
        held.append(lock)

    def note_release(self, lock: "_CheckedLockBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def note_release_all(self, lock: "_CheckedLockBase") -> int:
        """Drop EVERY held entry for `lock` (Condition._release_save on a
        recursively-held RLock releases all levels at once)."""
        held = self._held()
        n = sum(1 for h in held if h is lock)
        held[:] = [h for h in held if h is not lock]
        return n

    def note_restore(self, lock: "_CheckedLockBase", n: int) -> None:
        """Re-push `n` levels after Condition._acquire_restore — a
        restore of ordering already recorded, not a new edge."""
        self._held().extend([lock] * n)

    def _find_cycle(self, start: str, target: str) -> list[str] | None:
        """Path start ⇝ target in the edge graph (the new edge
        target → start then closes the cycle)."""
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == target:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def reports(self) -> list[CycleReport]:
        with self._mu:
            return list(self._reports)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._seen_edges.clear()
            self._same_class_seen.clear()
            self._reports.clear()


_registry = _Registry()


def reports() -> list[CycleReport]:
    """Cycle reports recorded so far (test hook)."""
    return _registry.reports()


def reset() -> None:
    """Clear the order graph and reports (test isolation)."""
    _registry.reset()


def _caller_site() -> str:
    """file:line of the lock's construction, skipping this module."""
    f = sys._getframe(2)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter teardown
        return "<unknown>"
    fname = os.path.basename(f.f_code.co_filename)
    return f"{fname}:{f.f_lineno}"


class _CheckedLockBase:
    _factory = staticmethod(_REAL_LOCK)
    reentrant = False

    def __init__(self, name: str | None = None):
        self._inner = self._factory()
        self.site = name or _caller_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # record BEFORE blocking: if this edge deadlocks for real, the
        # report has already been printed when the process wedges
        _registry.note_acquire(self, blocking=blocking,
                               bounded=timeout != -1)
        try:
            ok = self._inner.acquire(blocking, timeout)
        except BaseException:
            # interrupted mid-acquire (e.g. KeyboardInterrupt): the lock
            # was never taken — a phantom held entry would turn every
            # later acquisition into false reports
            _registry.note_release(self)
            raise
        if not ok:
            _registry.note_release(self)
        return ok

    def release(self):
        self._inner.release()
        _registry.note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # threading._after_fork calls this on every lock the module knows
        # about; without it the child hits AttributeError and a lock held
        # at fork time stays wedged forever. The child has exactly one
        # thread, so also drop any held-stack entries the forking thread
        # carried across — the inner lock is unlocked now, and stale
        # entries would manufacture false ordering edges.
        self._inner._at_fork_reinit()
        _registry.note_release_all(self)


class CheckedLock(_CheckedLockBase):
    _factory = staticmethod(_REAL_LOCK)


class CheckedRLock(_CheckedLockBase):
    _factory = staticmethod(_REAL_RLOCK)
    reentrant = True

    # Condition support: without these, threading.Condition falls back to
    # one plain release(), which only drops ONE recursion level of a
    # recursively-held RLock — cond.wait() would then park still holding
    # the lock and the checker itself would manufacture a deadlock
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        return (state, _registry.note_release_all(self))

    def _acquire_restore(self, saved):
        state, n = saved
        self._inner._acquire_restore(state)
        _registry.note_restore(self, n)


def _checked_lock_factory():
    return CheckedLock()


def _checked_rlock_factory():
    return CheckedRLock()


_installed = False


def install() -> None:
    """Swap threading.Lock/RLock for the instrumented wrappers.

    Locks created BEFORE install() stay plain — call it as early as
    possible (m3_tpu/__init__ does, under M3_TPU_LOCK_CHECK).  Condition
    and the other threading synchronizers build on the factories, so
    they inherit shadow locks transparently."""
    global _installed
    if _installed:
        return
    _installed = True
    threading.Lock = _checked_lock_factory
    threading.RLock = _checked_rlock_factory


def uninstall() -> None:
    """Restore the stdlib factories (test isolation)."""
    global _installed
    if not _installed:
        return
    _installed = False
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
