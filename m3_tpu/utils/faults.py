"""Deterministic fault injection for the write/read durability seams.

Role parity with the reference's failure-testing discipline (SURVEY §5:
dtest failure schedules, commitlog corruption fixtures) generalized into
one registry: production code declares named fault points at every
durability/network seam —

    faults.check("commitlog.fsync")          # may raise per the plan
    faults.torn_write(f, payload, "commitlog.flush")  # may tear the write

— and chaos tests (or an operator via environment) activate a *plan*:

    M3_TPU_FAULTS="commitlog.fsync=error:p0.5;peer.http=timeout" \
    M3_TPU_FAULTS_SEED=7 python ...

Determinism contract: every probabilistic decision draws from a per-point
RNG seeded by (seed, point), and the plan records the full fire schedule,
so the same spec + seed replays byte-identical fault schedules (the
checkpoint/recovery replay discipline TPU preemption forces everywhere).
The clock and sleep are injectable: `sleep` serves delay faults and
`clock` stamps each fire into `fire_times` — under a virtual clock the
whole fault timeline is reproducible, under the real one it correlates
fires with operator logs. (`schedule` itself carries no timestamps, so
schedule equality across runs holds under any clock.)

Overhead when disabled: `check` is one module-global load + None test —
no dict lookup, no lock — so the hooks stay in hot paths (per-datapoint
commitlog writes) for free.

Spec grammar (';'-separated rules, later rules for the same point are
tried after earlier ones):

    point=action[:p<prob>][:n<hit>][:x<max>][:d<seconds>]

    action  error    raise InjectedError (an OSError — real I/O failure
                     handlers treat it identically)
            timeout  raise InjectedTimeout (a TimeoutError)
            crash    raise SimulatedCrash (NOT an OSError: seams that
                     swallow I/O errors still die, like a real SIGKILL)
            torn     at torn_write points: write a deterministic prefix
                     of the payload, then SimulatedCrash; at plain check
                     points it degrades to crash
            delay    sleep d<seconds> (injectable), then continue
    p<f>    fire with probability f per hit (default 1.0)
    n<k>    fire only on the k-th hit of the point (1-based)
    x<k>    fire at most k times
    d<f>    delay seconds (delay action; default 0.01)
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from dataclasses import dataclass, field


class InjectedError(OSError):
    """Injected generic I/O failure."""


class InjectedTimeout(TimeoutError):
    """Injected timeout."""


class SimulatedCrash(Exception):
    """Injected process death at a fault point. Deliberately NOT an
    OSError: seams that tolerate I/O errors must still propagate this,
    the way no handler survives a SIGKILL."""


@dataclass
class FaultRule:
    point: str
    action: str                  # error | timeout | crash | torn | delay
    probability: float = 1.0
    fire_on: int | None = None   # n<k>: fire only on this hit (1-based)
    max_fires: int | None = None # x<k>: total fire budget
    delay_s: float = 0.01        # d<f>: for the delay action
    fires: int = field(default=0, compare=False)


_ACTIONS = ("error", "timeout", "crash", "torn", "delay")


def parse_spec(spec: str) -> list[FaultRule]:
    rules: list[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, sep, rhs = part.partition("=")
        if not sep or not point.strip():
            raise ValueError(f"bad fault rule (want point=action): {part!r}")
        fields = rhs.split(":")
        action = fields[0].strip()
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} in {part!r}")
        rule = FaultRule(point.strip(), action)
        for mod in fields[1:]:
            mod = mod.strip()
            if not mod:
                continue
            kind, val = mod[0], mod[1:]
            if kind == "p":
                rule.probability = float(val)
            elif kind == "n":
                rule.fire_on = int(val)
            elif kind == "x":
                rule.max_fires = int(val)
            elif kind == "d":
                rule.delay_s = float(val)
            else:
                raise ValueError(f"unknown fault modifier {mod!r} in {part!r}")
        rules.append(rule)
    return rules


class FaultPlan:
    """A parsed, seeded fault schedule. All counter/RNG state is guarded
    by one lock (see tools/race_check.py's registry stress workload), and
    every decision is appended to `schedule` so tests can assert that a
    seed replays the exact same run."""

    def __init__(self, rules: list[FaultRule], seed: int = 0,
                 clock=time.monotonic, sleep=time.sleep):
        self.seed = seed
        self.clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._rules: dict[str, list[FaultRule]] = {}
        for r in rules:
            self._rules.setdefault(r.point, []).append(r)
        self._hits: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        # (point, hit_index, action) per fire, in decision order
        self.schedule: list[tuple[str, int, str]] = []
        # clock() at each fire, aligned with schedule: virtual clocks give
        # reproducible timelines, the real one correlates with logs
        self.fire_times: list[float] = []

    def _rng(self, point: str) -> random.Random:
        rng = self._rngs.get(point)
        if rng is None:
            rng = self._rngs[point] = random.Random(f"{self.seed}:{point}")
        return rng

    def decide(self, point: str) -> FaultRule | None:
        """Count a hit at `point`; return the rule that fires, if any."""
        rules = self._rules.get(point)
        if rules is None:
            return None
        with self._lock:
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            for rule in rules:
                if rule.max_fires is not None and rule.fires >= rule.max_fires:
                    continue
                if rule.fire_on is not None and hit != rule.fire_on:
                    continue
                if rule.probability < 1.0 and \
                        self._rng(point).random() >= rule.probability:
                    continue
                rule.fires += 1
                self.schedule.append((point, hit, rule.action))
                self.fire_times.append(self.clock())
                return rule
            return None

    def raise_for(self, rule: FaultRule, point: str, ctx: dict) -> None:
        where = f"injected fault at {point}" + (f" {ctx}" if ctx else "")
        if rule.action == "error":
            raise InjectedError(where)
        if rule.action == "timeout":
            raise InjectedTimeout(where)
        if rule.action in ("crash", "torn"):
            raise SimulatedCrash(where)
        if rule.action == "delay":
            self._sleep(rule.delay_s)
            return
        raise AssertionError(f"unhandled fault action {rule.action}")

    def check(self, point: str, ctx: dict) -> None:
        rule = self.decide(point)
        if rule is not None:
            self.raise_for(rule, point, ctx)

    def cut(self, point: str, length: int) -> int:
        """Deterministic tear offset in [1, length) for a torn write."""
        if length <= 1:
            return 0
        with self._lock:
            return 1 + int(self._rng(point + "#cut").random() * (length - 1))

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)


# the one module-level flag: None = injection disabled, every hook is a
# single load+is-None test
_ACTIVE: FaultPlan | None = None


def enabled() -> bool:
    return _ACTIVE is not None


def plan() -> FaultPlan | None:
    return _ACTIVE


def configure(spec: str | None = None, seed: int | None = None,
              clock=time.monotonic, sleep=time.sleep) -> FaultPlan:
    """Activate a fault plan from `spec` (default: $M3_TPU_FAULTS) with
    `seed` (default: $M3_TPU_FAULTS_SEED, else 0)."""
    global _ACTIVE
    if spec is None:
        spec = os.environ.get("M3_TPU_FAULTS", "")
    if seed is None:
        seed = int(os.environ.get("M3_TPU_FAULTS_SEED", "0"))
    p = FaultPlan(parse_spec(spec), seed=seed, clock=clock, sleep=sleep)
    _ACTIVE = p
    return p


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


def escalate(exc: BaseException | None = None) -> None:
    """Service-entrypoint catch hook: when a chaos rig armed
    ``M3_TPU_FAULTS_EXIT=1`` in a SPAWNED service process, a
    SimulatedCrash that reached a catch block becomes a REAL process
    death (``os._exit(137)``, SIGKILL parity) instead of unwinding into
    a 500 in a process that lives on. Call it with the caught exception
    (no-op for non-crash exceptions) or bare from an
    ``except SimulatedCrash`` block. Unarmed (the default, and every
    in-process test), this is a no-op and the exception propagates."""
    if exc is not None and not isinstance(exc, SimulatedCrash):
        return
    if os.environ.get("M3_TPU_FAULTS_EXIT") == "1":
        os._exit(137)


@contextlib.contextmanager
def active(spec: str, seed: int = 0, clock=time.monotonic, sleep=time.sleep):
    """Scoped activation for tests: always disables on exit."""
    p = configure(spec, seed=seed, clock=clock, sleep=sleep)
    try:
        yield p
    finally:
        disable()


def check(point: str, **ctx) -> None:
    """Fault point: no-op unless a plan is active and a rule fires."""
    p = _ACTIVE
    if p is None:
        return
    p.check(point, ctx)


def torn_write(f, data: bytes, point: str) -> None:
    """Write `data` to file object `f`, or — when a rule fires at `point`
    — inject: `torn` writes a deterministic prefix then raises
    SimulatedCrash (the kill-at-an-arbitrary-byte-offset case every
    durability format must survive); other actions raise before any byte
    lands."""
    p = _ACTIVE
    if p is None:
        f.write(data)
        return
    rule = p.decide(point)
    if rule is None:
        f.write(data)
        return
    if rule.action == "torn":
        k = p.cut(point, len(data))
        if k:
            f.write(data[:k])
            f.flush()
        raise SimulatedCrash(f"torn write at {point} ({k}/{len(data)} bytes)")
    p.raise_for(rule, point, {})


class _FaultyIO:
    """File-object proxy whose writes go through torn_write."""

    def __init__(self, f, point: str):
        self._f = f
        self._point = point

    def write(self, data: bytes):
        torn_write(self._f, data, self._point)
        return len(data)

    def __getattr__(self, item):
        return getattr(self._f, item)


def wrap_io(f, point: str):
    """Wrap a file object so its writes hit `point` (identity when
    injection is disabled — zero proxy overhead in production)."""
    if _ACTIVE is None:
        return f
    return _FaultyIO(f, point)


# env-driven activation at import: a process launched with M3_TPU_FAULTS
# set runs its whole life under the plan (chaos harnesses spawn real
# dbnode/kvd/aggregator processes this way)
if os.environ.get("M3_TPU_FAULTS"):
    configure()
