"""Time units and conversions.

Wire-compatible with the reference time unit enum
(/root/reference/src/x/time/unit.go:31-41): the byte values written into
M3TSZ streams for time-unit changes must match so that streams are
bit-identical with the reference encoder.
"""

from __future__ import annotations

import enum


class TimeUnit(enum.IntEnum):
    """Time unit enum; integer values are the wire format."""

    NONE = 0
    SECOND = 1
    MILLISECOND = 2
    MICROSECOND = 3
    NANOSECOND = 4
    MINUTE = 5
    HOUR = 6
    DAY = 7
    YEAR = 8


_UNIT_NANOS = {
    TimeUnit.NONE: 0,
    TimeUnit.SECOND: 1_000_000_000,
    TimeUnit.MILLISECOND: 1_000_000,
    TimeUnit.MICROSECOND: 1_000,
    TimeUnit.NANOSECOND: 1,
    TimeUnit.MINUTE: 60 * 1_000_000_000,
    TimeUnit.HOUR: 3600 * 1_000_000_000,
    TimeUnit.DAY: 24 * 3600 * 1_000_000_000,
    TimeUnit.YEAR: 365 * 24 * 3600 * 1_000_000_000,
}


def unit_value_ns(unit: TimeUnit) -> int:
    """Duration of one unit in nanoseconds. Raises for NONE."""
    v = _UNIT_NANOS[TimeUnit(unit)]
    if v == 0:
        raise ValueError("time unit NONE has no duration")
    return v


def unit_is_valid(unit: int) -> bool:
    try:
        u = TimeUnit(unit)
    except ValueError:
        return False
    return u != TimeUnit.NONE


def to_normalized(duration_ns: int, unit_ns: int) -> int:
    """Truncating division like Go's time.Duration / time.Duration."""
    # Go integer division truncates toward zero; Python // floors.
    q = abs(duration_ns) // unit_ns
    return q if duration_ns >= 0 else -q


def from_normalized(normalized: int, unit_ns: int) -> int:
    return normalized * unit_ns


def initial_time_unit(start_ns: int, unit: TimeUnit) -> TimeUnit:
    """A unit is usable from the start only if start is a multiple of it.

    Mirrors initialTimeUnit (reference m3tsz/timestamp_encoder.go:248-259).
    """
    if not unit_is_valid(unit):
        return TimeUnit.NONE
    if start_ns % unit_value_ns(unit) == 0:
        return TimeUnit(unit)
    return TimeUnit.NONE
