"""Fast TPU-tunnel reachability probe — no jax import, bounded seconds.

Why this exists: the axon PJRT client initializes by polling
``GET http://<pool-svc>:8083/init`` every ~10s *forever*. When the tunnel
behind the relay is down, ``jax.devices()`` therefore hangs every process
that touches jax with ``JAX_PLATFORMS=axon`` — rounds 1 and 2 lost every
TPU bench budget (420 s each) and six 13-minute measurement attempts to
exactly this (see TPU_STATUS.md for the captured evidence).

This module answers "is a terminal reachable?" in under ~3 seconds with
plain sockets so callers can fall back to CPU immediately instead of
hanging, and so a background watcher can cheaply poll for the tunnel
coming alive.

Probed endpoints (in order):
- ``127.0.0.1:8083`` — the axon terminal's stateless HTTP port; the
  PJRT client's own init poll target (captured on a local listener:
  ``GET /init?rank=...&topology=v5e:1x1x1&n_slices=1``).
- ``127.0.0.1:2024`` — the relay listener present in this image. A live
  relay proxies HTTP through; a dead one accepts the TCP handshake and
  immediately closes (observed behavior while the tunnel is down).
"""

from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field

INIT_PATH = "/init?rank=4294967295&topology=v5e:1x1x1&n_slices=1"
CANDIDATES = (("127.0.0.1", 8083), ("127.0.0.1", 2024))


@dataclass
class ProbeResult:
    live: bool
    detail: list[str] = field(default_factory=list)

    def __bool__(self) -> bool:  # pragma: no cover - convenience
        return self.live


def _probe_http(host: str, port: int, timeout: float) -> tuple[bool, str]:
    """True if an HTTP server answers the axon /init poll on host:port."""
    try:
        s = socket.create_connection((host, port), timeout=timeout)
    except OSError as e:
        return False, f"{host}:{port} connect failed: {e}"
    try:
        s.settimeout(timeout)
        req = (
            f"GET {INIT_PATH} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\nAccept: */*\r\n\r\n"
        )
        s.sendall(req.encode())
        data = s.recv(256)
    except OSError as e:
        return False, f"{host}:{port} no response: {e}"
    finally:
        s.close()
    if not data:
        # accept-then-EOF: dead relay endpoint (tunnel down)
        return False, f"{host}:{port} accepted then closed (dead relay)"
    if data.startswith(b"HTTP/"):
        return True, f"{host}:{port} answered: {data[:60]!r}"
    return False, f"{host}:{port} non-HTTP reply: {data[:60]!r}"


def probe(timeout: float = 3.0) -> ProbeResult:
    """Probe all candidate endpoints; live if any answers HTTP."""
    details = []
    for host, port in CANDIDATES:
        ok, msg = _probe_http(host, port, timeout)
        details.append(msg)
        if ok:
            return ProbeResult(True, details)
    return ProbeResult(False, details)


def wait_live(total_s: float, interval_s: float = 30.0) -> ProbeResult:
    """Poll until live or total_s elapses; returns the last result."""
    deadline = time.time() + total_s
    while True:
        r = probe()
        if r.live or time.time() >= deadline:
            return r
        time.sleep(min(interval_s, max(0.0, deadline - time.time())))


if __name__ == "__main__":  # pragma: no cover
    r = probe()
    print(f"live={r.live}")
    for d in r.detail:
        print(f"  {d}")
    raise SystemExit(0 if r.live else 1)
