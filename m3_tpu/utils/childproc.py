"""Shared helpers for running jax work in defended child processes.

The axon TPU relay dials at interpreter startup and can hang every python
process when the tunnel is down; driver-facing entry points (bench.py,
__graft_entry__.dryrun_multichip) therefore run their jax work in child
processes with this scrubbed env. One definition here so a tunnel-related
fix lands in every caller.
"""

from __future__ import annotations

import os

# PALLAS_AXON_POOL_IPS= skips the relay dial entirely;
# JAX_PLATFORMS=cpu prevents a half-registered axon backend being chosen
SCRUBBED_TPU_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
}


def scrubbed_env(n_devices: int | None = None) -> dict:
    """A copy of os.environ that cannot touch the TPU relay; optionally
    forces an n_devices virtual CPU mesh."""
    env = dict(os.environ)
    env.update(SCRUBBED_TPU_ENV)
    if n_devices is not None:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    return env


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def tail(text: str | bytes | None, n: int = 4000) -> str:
    if not text:
        return ""
    if isinstance(text, bytes):
        text = text.decode(errors="replace")
    return text[-n:]
