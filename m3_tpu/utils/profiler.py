"""Always-on profiling & saturation plane: sampling profiler, lock-wait
profiling, and stall watchdogs.

The platform can trace a request (utils/trace), explain a plan
(query/explain) and price a tenant (utils/tenantlimits) — this module
covers where time goes when nothing is computing: lock waits under the
consensus persist-before-ack sections, saturated bounded queues silently
dropping, periodic loops wedged mid-cycle. Three cooperating pieces, all
cheap enough to leave armed in production:

**Sampling profiler** — a daemon thread walks ``sys._current_frames()``
at a jittered ~19 Hz (prime-ish, so it cannot phase-lock with 10 ms/1 s
periodic work) and aggregates folded stacks per THREAD ROLE (thread
names normalized: ``repair-daemon``, ``telemetry-export-coordinator``,
``ThreadPoolExecutor``, ...) into a bounded table. Served as
collapsed-stack text (the flamegraph.pl wire format) and top-N self-time
JSON at ``/debug/profile`` on all four services. ``M3_TPU_PROFILE``
arms it at service start (a number > 1 sets the rate); POST
``/debug/profile {"enabled": true}`` toggles it live. The telemetry
exporter ships table snapshots with the PR-6 cursor discipline (a
snapshot ships at most once; no new samples, nothing shipped).

**Lock-wait profiling** — ``M3_TPU_LOCK_PROFILE=1`` (read at ``m3_tpu``
import, like the shadow-lock checker) swaps ``threading.Lock/RLock``
for wrappers keyed by CONSTRUCTION SITE (lockcheck's lock-class
semantics: every ``Shard._lock`` is one class however many shards
exist). The fast path is a non-blocking try-acquire plus one counter
increment — an uncontended acquire pays no clock read at all (bench #10
holds the armed write hot path inside the 0.85 noise bar). A failed
trylock IS the contention signal: only then does the wrapper time the
blocking acquire and land the wait in the per-class histogram, so
"which lock burns our p99" is a measured table — the consensus fsync
sections ROADMAP #2 wants to dissolve become a list, not a waiver file.
The accumulated per-class histograms publish into the metrics registry
as ``lock_wait_seconds{cls=...}`` at every snapshot, so
``histogram_quantile`` over lock-wait works on /metrics, via the
exporter, AND through the ``_m3_system`` self-scrape.

**Stall watchdog** — every periodic loop (aggregator flush, repair
cycle, raft tick, service ticks, self-scrape, exporter drain) registers
a heartbeat and beats it once per iteration. A checker thread flags
loops whose last beat is older than ``miss_factor`` intervals: one
stall tracepoint + counter per EPISODE (recovery clears, a new wedge
fires again), with the wedged thread's captured stack in the event ring
— the post-mortem a hung loop never writes for itself.

Composability: the profiled lock wrapper wraps whatever
``threading.Lock`` currently is, so under ``M3_TPU_LOCK_CHECK`` the
shadow-lock checker keeps seeing every blocking acquisition (ordering
edges are recorded by the inner checked lock).
"""

from __future__ import annotations

import bisect
import json
import os
import re
import sys
import threading
import time
import traceback
from collections import deque

from m3_tpu.utils import lockcheck
from m3_tpu.utils.instrument import (
    DEFAULT_BUCKETS,
    Scope,
    default_registry,
    register_snapshot_hook,
)

# raw (never-instrumented) lock factory: the profiler's own bookkeeping
# must not recurse through the profiled wrappers it implements
_RAW_LOCK = lockcheck._REAL_LOCK


DEFAULT_HZ = 19.0  # prime-ish; jittered further per sleep


def _truthy(value: str | None) -> bool:
    return lockcheck.env_enabled(value)


# ---------------------------------------------------------------------------
# sampling profiler
# ---------------------------------------------------------------------------

_ROLE_RE = re.compile(r"[-_]?\d+(?:_\d+)?$")


def thread_role(name: str) -> str:
    """Normalize a thread name to its ROLE: strip instance counters so
    every worker of a kind folds into one row (``Thread-12 (worker)`` ->
    ``Thread``, ``ThreadPoolExecutor-0_3`` -> ``ThreadPoolExecutor``,
    ``repair-daemon`` stays itself)."""
    head = (name or "").partition(" ")[0]
    return _ROLE_RE.sub("", head) or "thread"


def _fold_frame(frame, max_depth: int = 48) -> str:
    """Root-first ``file:func;file:func;...`` folded stack for one live
    frame (the collapsed-stack convention flamegraph tooling eats)."""
    parts: list[str] = []
    f = frame
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Wall-clock sampling profiler over ``sys._current_frames()``.

    The aggregate table is bounded (``max_stacks`` distinct
    (role, folded-stack) keys): on overflow the current minimum-count
    entry is evicted and its samples land in ``evicted_samples`` — the
    table can mis-attribute the cold tail, never grow without bound."""

    def __init__(self, hz: float = DEFAULT_HZ, max_stacks: int = 2048,
                 registry=None, clock=time.monotonic):
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.clock = clock
        self.enabled = False
        self.samples = 0           # sampling passes taken
        self.evicted_samples = 0   # samples lost to table eviction
        self._table: dict[tuple[str, str], int] = {}
        self._lock = _RAW_LOCK()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._registry = registry
        self._observe_pass = None  # lazy histogram handle

    def _scope(self):
        return (self._registry or default_registry()).root_scope("profiler")

    # -- recording --

    def _record(self, role: str, folded: str, count: int = 1) -> None:
        key = (role, folded)
        with self._lock:
            cur = self._table.get(key)
            if cur is not None:
                self._table[key] = cur + count
                return
            if len(self._table) >= self.max_stacks:
                # evict the current cold-tail entry; its samples stay
                # accounted (evicted_samples) so totals never lie
                victim = min(self._table, key=self._table.get)
                self.evicted_samples += self._table.pop(victim)
            self._table[key] = count

    def sample_once(self) -> int:
        """One sampling pass over every live thread (the sampler thread
        itself excluded). Returns threads sampled."""
        if self._observe_pass is None:
            self._observe_pass = self._scope().histogram_handle(
                "sample_seconds")
        t0 = time.perf_counter()
        names = {t.ident: t.name for t in threading.enumerate()}
        own = threading.get_ident()
        n = 0
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            self._record(thread_role(names.get(tid, "")), _fold_frame(frame))
            n += 1
        with self._lock:
            self.samples += 1
        self._observe_pass(time.perf_counter() - t0)
        return n

    # -- rendering --

    def collapsed(self) -> str:
        """The whole table in collapsed-stack text: one
        ``role;frame;frame count`` line per aggregated stack."""
        with self._lock:
            items = sorted(self._table.items(),
                           key=lambda kv: -kv[1])
        return "\n".join(f"{role};{folded} {count}"
                         for (role, folded), count in items) + \
            ("\n" if items else "")

    def top(self, n: int = 20) -> list[dict]:
        """Top-N frames by SELF samples (leaf of the folded stack), with
        total (anywhere-on-stack) samples alongside."""
        self_c: dict[str, int] = {}
        total_c: dict[str, int] = {}
        with self._lock:
            items = list(self._table.items())
        for (_role, folded), count in items:
            frames = folded.split(";")
            if not frames:
                continue
            self_c[frames[-1]] = self_c.get(frames[-1], 0) + count
            for fr in set(frames):
                total_c[fr] = total_c.get(fr, 0) + count
        ranked = sorted(self_c.items(), key=lambda kv: -kv[1])[:n]
        return [{"frame": fr, "self": c, "total": total_c.get(fr, c)}
                for fr, c in ranked]

    def status(self) -> dict:
        with self._lock:
            stacks = len(self._table)
        return {"enabled": self.enabled, "hz": self.hz,
                "samples": self.samples, "stacks": stacks,
                "evicted_samples": self.evicted_samples,
                "max_stacks": self.max_stacks}

    def export_since(self, cursor: int) -> tuple[dict | None, int]:
        """Cursor-disciplined snapshot for the telemetry exporter: the
        current table summary if sampling advanced past `cursor`, else
        None — each sampling epoch ships at most once."""
        if self.samples <= cursor:
            return None, cursor
        return ({"samples": self.samples, "top": self.top(50),
                 "evicted_samples": self.evicted_samples}, self.samples)

    def reset(self) -> None:
        with self._lock:
            self._table.clear()
            self.samples = 0
            self.evicted_samples = 0

    # -- lifecycle --

    def start(self, hz: float | None = None) -> None:
        if hz is not None and hz > 0:
            self.hz = float(hz)
        self.enabled = True
        self._wake.set()
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            import random

            rng = random.Random(os.getpid())
            while not self._stop.is_set():
                if not self.enabled:
                    # parked: clear the (set-by-start) wake flag so the
                    # wait actually blocks, re-checking enabled after
                    # the clear so a concurrent start() is never missed
                    self._wake.clear()
                    if not self.enabled:
                        self._wake.wait(0.25)
                    continue
                try:
                    self.sample_once()
                except Exception:  # noqa: BLE001 - a torn frame walk must
                    pass           # never kill the sampler
                # jittered period: mean 1/hz, +-25% so the sampler can't
                # alias against the platform's own periodic loops
                period = 1.0 / max(self.hz, 0.1)
                self._stop.wait(period * (0.75 + 0.5 * rng.random()))

        self._thread = threading.Thread(target=loop, name="profiler-sampler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop sampling AND the thread (tests); `enabled = False` alone
        parks the thread for a cheap runtime toggle."""
        self.enabled = False
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
        self._wake.clear()


# ---------------------------------------------------------------------------
# lock-wait profiling
# ---------------------------------------------------------------------------

MAX_LOCK_CLASSES = 512  # construction sites are code-defined; the cap is
#                         a backstop against lock-constructing loops

_stats_lock = _RAW_LOCK()
_lock_classes: dict[str, "_LockClass"] = {}


class _LockClass:
    """Accumulated wait statistics for one lock construction site."""

    __slots__ = ("site", "acquisitions", "contended", "wait_total_s",
                 "wait_max_s", "hist_counts", "hist_sum",
                 "_pub_counts", "_pub_sum", "_pub_acq", "_pub_contended")

    def __init__(self, site: str):
        self.site = site
        # racy (GIL-interleaved +=) by design: the uncontended fast path
        # must not take any lock; occasional lost increments are noise
        self.acquisitions = 0
        self.contended = 0
        self.wait_total_s = 0.0
        self.wait_max_s = 0.0
        self.hist_counts = [0] * (len(DEFAULT_BUCKETS) + 1)
        self.hist_sum = 0.0
        # publish cursors: deltas since the last registry publish
        self._pub_counts = [0] * (len(DEFAULT_BUCKETS) + 1)
        self._pub_sum = 0.0
        self._pub_acq = 0
        self._pub_contended = 0

    def note_wait(self, dt: float) -> None:
        """One contended acquisition (the trylock failed) — exact,
        under the stats lock (contention is rare; that's the point)."""
        i = bisect.bisect_left(DEFAULT_BUCKETS, dt)
        with _stats_lock:
            self.contended += 1
            self.wait_total_s += dt
            if dt > self.wait_max_s:
                self.wait_max_s = dt
            self.hist_counts[i] += 1
            self.hist_sum += dt

    def to_doc(self) -> dict:
        with _stats_lock:
            contended, total, mx = (self.contended, self.wait_total_s,
                                    self.wait_max_s)
        return {"site": self.site, "acquisitions": self.acquisitions,
                "contended": contended,
                "wait_total_ms": round(total * 1e3, 3),
                "wait_max_ms": round(mx * 1e3, 3)}


def _lock_class(site: str) -> _LockClass:
    cls = _lock_classes.get(site)
    if cls is not None:
        return cls
    with _stats_lock:
        cls = _lock_classes.get(site)
        if cls is None:
            if len(_lock_classes) >= MAX_LOCK_CLASSES:
                site = "other"
                cls = _lock_classes.get(site)
                if cls is not None:
                    return cls
            cls = _lock_classes[site] = _LockClass(site)
    return cls


def _construction_site() -> str:
    """file:line of the profiled lock's construction (the lock-class
    key lockcheck uses), skipping this module's own frames."""
    f = sys._getframe(1)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter teardown
        return "<unknown>"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


class _ProfiledLockBase:
    """Fast path: one non-blocking C acquire + one counter increment —
    no clock reads on an uncontended acquire. A failed trylock IS the
    contention signal; only then is the blocking acquire timed and the
    wait recorded. (A profiled RLock's reentrant re-acquire also takes
    the trylock fast path — the owner's acquire(False) succeeds.)

    Composing over the shadow-lock checker: the wrapper wraps whatever
    ``threading.Lock`` currently is, so a checked inner lock still
    records held-stack state on every acquire; ordering EDGES are only
    recorded on the contended path (the uncontended trylock is edge-free
    by lockcheck's own trylock rule) — arm the checker without the
    profiler when hunting ordering bugs."""

    _reentrant = False

    def __init__(self):
        site = _construction_site()
        self._cls = _lock_class(site)
        inner = self._inner_factory()
        # hand a checked inner lock OUR construction site (it would
        # otherwise key every lock in the tree to this module's line)
        if hasattr(inner, "site"):
            inner.site = site
        self._inner = inner
        self._try = inner.acquire
        self._release = inner.release

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._try(False):
            self._cls.acquisitions += 1
            return True
        if not blocking:
            return False
        return self._slow(timeout)

    def _slow(self, timeout: float = -1):
        t0 = time.perf_counter()
        ok = self._try(True, timeout)
        dt = time.perf_counter() - t0
        cls = self._cls
        cls.acquisitions += 1
        # the wait is recorded whether or not the acquire ultimately
        # succeeded: a bounded acquire that times out spent exactly
        # timeout seconds stuck behind the holder — the WORST waits —
        # and skipping it would rank a perpetually-timing-out gate as
        # uncontended
        cls.note_wait(dt)
        return ok

    def release(self):
        self._release()

    def __enter__(self):
        # flattened fast path: `with lock:` is the hot idiom
        if self._try(False):
            self._cls.acquisitions += 1
        else:
            self._slow()
        return self

    def __exit__(self, *exc):
        self._release()
        return False

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()


class ProfiledLock(_ProfiledLockBase):
    pass


class ProfiledRLock(_ProfiledLockBase):
    _reentrant = True

    # Condition support: delegate the save/restore protocol so
    # cond.wait() on a recursively-held profiled RLock releases every
    # level (exactly the lockcheck wrapper's reasoning)
    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, saved):
        self._inner._acquire_restore(saved)


_install_lock = _RAW_LOCK()
_installed = False
_prev_factories: tuple | None = None


def install_lock_profiling() -> None:
    """Swap threading.Lock/RLock for the timed wrappers, wrapping
    whatever the factories currently are (so the shadow-lock checker,
    if installed first, keeps its ordering edges). Locks created BEFORE
    install stay raw — m3_tpu/__init__ installs under
    ``M3_TPU_LOCK_PROFILE`` so service-lifetime locks are all covered;
    the metrics registry's own lock (created at instrument import) stays
    deliberately raw, keeping the hottest lock overhead-free."""
    global _installed, _prev_factories
    with _install_lock:
        if _installed:
            return
        _installed = True
        _prev_factories = (threading.Lock, threading.RLock)
        prev_lock, prev_rlock = _prev_factories

        class _Lock(ProfiledLock):
            _inner_factory = staticmethod(prev_lock)

        class _RLock(ProfiledRLock):
            _inner_factory = staticmethod(prev_rlock)

        threading.Lock = _Lock
        threading.RLock = _RLock


def uninstall_lock_profiling() -> None:
    """Restore the previous factories (test isolation)."""
    global _installed, _prev_factories
    with _install_lock:
        if not _installed:
            return
        _installed = False
        threading.Lock, threading.RLock = _prev_factories
        _prev_factories = None


def lock_profiling_installed() -> bool:
    return _installed


def lock_classes(min_contended: int = 0) -> list[dict]:
    """The contended-lock table, hottest (total wait) first."""
    with _stats_lock:
        classes = list(_lock_classes.values())
    docs = [c.to_doc() for c in classes]
    docs = [d for d in docs if d["contended"] >= min_contended]
    docs.sort(key=lambda d: -d["wait_total_ms"])
    return docs


def reset_lock_stats() -> None:
    with _stats_lock:
        _lock_classes.clear()


def _publish_lock_stats(registry) -> None:
    """Snapshot hook: fold per-class wait-histogram DELTAS into the
    default metrics registry (``lock_wait_seconds{cls=...}`` plus
    acquisition/contention counters), so /metrics, the exporter and the
    ``_m3_system`` self-scrape all see lock waits as first-class
    histograms — histogram_quantile over lock-wait end to end."""
    if registry is not default_registry():
        return  # lock stats are process-global; publish once, to the
        #         process registry (private test registries stay clean)
    with _stats_lock:
        deltas = []
        for cls in _lock_classes.values():
            dc = [a - b for a, b in zip(cls.hist_counts, cls._pub_counts)]
            dsum = cls.hist_sum - cls._pub_sum
            dacq = cls.acquisitions - cls._pub_acq
            dcont = cls.contended - cls._pub_contended
            if not any(dc) and dacq <= 0:
                continue
            cls._pub_counts = list(cls.hist_counts)
            cls._pub_sum = cls.hist_sum
            cls._pub_acq = cls.acquisitions
            cls._pub_contended = cls.contended
            deltas.append((cls.site, dc, dsum, dacq, dcont))
    for site, dc, dsum, dacq, dcont in deltas:
        tags = (("cls", site),)
        if any(dc):
            registry.merge_histogram("lock.wait_seconds", tags,
                                     DEFAULT_BUCKETS, dc, dsum)
        scope = Scope(registry, "lock", tags)
        if dacq > 0:
            scope.counter("acquisitions", dacq)
        if dcont > 0:
            scope.counter("contended", dcont)


register_snapshot_hook(_publish_lock_stats)


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------

class Heartbeat:
    """One registered periodic loop's handle: call ``beat()`` once per
    iteration; ``close()`` unregisters (service shutdown)."""

    __slots__ = ("name", "interval_s", "last_beat", "beats", "stalled",
                 "stalls", "recovered", "tid", "_wd")

    def __init__(self, name: str, interval_s: float, wd: "Watchdog"):
        self.name = name
        self.interval_s = float(interval_s)
        self.last_beat = wd.clock()
        self.beats = 0
        self.stalled = False
        self.stalls = 0
        self.recovered = 0
        self.tid: int | None = None
        self._wd = wd

    def beat(self) -> None:
        wd = self._wd
        with wd._lock:
            self.last_beat = wd.clock()
            self.beats += 1
            if self.tid is None:
                self.tid = threading.get_ident()
            if self.stalled:
                self.stalled = False
                self.recovered += 1
                wd._on_recover(self)

    def close(self) -> None:
        self._wd.unregister(self.name)

    def to_doc(self, now: float) -> dict:
        return {"loop": self.name, "interval_s": self.interval_s,
                "beats": self.beats,
                "last_beat_age_s": round(now - self.last_beat, 3),
                "stalled": self.stalled, "stalls": self.stalls,
                "recovered": self.recovered}


class Watchdog:
    """Flags periodic loops that miss ``miss_factor`` intervals: one
    stall event per episode (tracepoint + counter + the wedged thread's
    captured stack), recovery clears so the next wedge fires again."""

    EVENT_RING = 256

    def __init__(self, miss_factor: float = 3.0, registry=None,
                 clock=time.monotonic, check_period_s: float = 0.25):
        self.miss_factor = float(miss_factor)
        self.clock = clock
        self.check_period_s = check_period_s
        self._lock = _RAW_LOCK()
        self._loops: dict[str, Heartbeat] = {}
        # the watchdog's own evidence ring: deliberately outside the
        # saturation plane — overwriting old stall events is its design,
        # and the plane's implementation must not feed back into itself
        # m3lint: disable=inv-queue-gauge
        self._events: deque[dict] = deque(maxlen=self.EVENT_RING)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _scope(self):
        return (self._registry or default_registry()).root_scope("watchdog")

    # -- registration --

    def register(self, name: str, interval_s: float) -> Heartbeat:
        """Register (or re-register: latest wins) a periodic loop."""
        hb = Heartbeat(name, interval_s, self)
        with self._lock:
            self._loops[name] = hb
        return hb

    def unregister(self, name: str) -> None:
        with self._lock:
            self._loops.pop(name, None)

    # -- checking --

    def _capture_stack(self, tid: int | None) -> str:
        if tid is None:
            return ""
        frame = sys._current_frames().get(tid)
        if frame is None:
            return ""
        return "".join(traceback.format_stack(frame))

    def _on_recover(self, hb: Heartbeat) -> None:
        # called under self._lock from Heartbeat.beat
        self._events.append({"kind": "recover", "loop": hb.name,
                             "t_unix": time.time()})

    def check_once(self, now: float | None = None) -> list[dict]:
        """One pass over registered loops; returns NEW stall events."""
        from m3_tpu.utils import trace

        now = now if now is not None else self.clock()
        fired: list[dict] = []
        with self._lock:
            loops = list(self._loops.values())
        for hb in loops:
            with self._lock:
                age = now - hb.last_beat
                # floor the interval: a 0s-interval registration (tests,
                # tick-driven monitors) must not read as instantly stalled
                if hb.stalled or \
                        age <= max(hb.interval_s, 0.1) * self.miss_factor:
                    continue
                # fires ONCE per episode: stalled stays set until a beat
                hb.stalled = True
                hb.stalls += 1
                tid = hb.tid
            ev = {"kind": "stall", "loop": hb.name, "t_unix": time.time(),
                  "age_s": round(age, 3),
                  "stack": self._capture_stack(tid)}
            with self._lock:
                self._events.append(ev)
            fired.append(ev)
            self._scope().subscope("loop", loop=hb.name).counter("stalls")
            with trace.span(trace.WATCHDOG_STALL, loop=hb.name,
                            age_s=round(age, 3)):
                pass
        return fired

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def status(self) -> dict:
        now = self.clock()
        with self._lock:
            loops = [hb.to_doc(now) for hb in self._loops.values()]
            events = list(self._events)[-32:]
        return {"armed": self._thread is not None,
                "miss_factor": self.miss_factor,
                "loops": sorted(loops, key=lambda d: d["loop"]),
                "recent_events": events}

    def reset(self) -> None:
        with self._lock:
            self._loops.clear()
            self._events.clear()

    # -- lifecycle --

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.check_period_s):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001 - the watchdog must
                    pass           # outlive anything it watches

        self._thread = threading.Thread(target=loop, name="stall-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# process singletons + the /debug/profile surface
# ---------------------------------------------------------------------------

_default_profiler = SamplingProfiler()
_default_watchdog = Watchdog()


def default_profiler() -> SamplingProfiler:
    return _default_profiler


def default_watchdog() -> Watchdog:
    return _default_watchdog


def register_heartbeat(name: str, interval_s: float) -> Heartbeat:
    """Register a loop on the process watchdog (services use this)."""
    return _default_watchdog.register(name, interval_s)


def _rss_bytes() -> int:
    # the shared reader (incl. the darwin getrusage fallback): both
    # observability surfaces must report the same RSS
    from m3_tpu.utils.selfscrape import rss_bytes

    return rss_bytes()


def env_hz(value: str | None) -> float | None:
    """M3_TPU_PROFILE -> sampling rate: truthy enables at the default
    rate; a number > 1 sets the rate; falsy/None disables."""
    if not _truthy(value):
        return None
    try:
        n = float(value.strip())
    except (ValueError, AttributeError):
        return DEFAULT_HZ
    return n if n > 1 else DEFAULT_HZ


def arm_from_env(service: str = "") -> bool:
    """Service-entrypoint hook: arm the sampler + watchdog checker when
    ``M3_TPU_PROFILE`` asks for it. Idempotent; returns armed-ness."""
    hz = env_hz(os.environ.get("M3_TPU_PROFILE"))
    if hz is None:
        return False
    _default_profiler.start(hz)
    _default_watchdog.start()
    return True


def profile_payload(top_n: int = 20) -> dict:
    """The /debug/profile JSON body, shared by all four services."""
    return {
        "profiler": {**_default_profiler.status(),
                     "top": _default_profiler.top(top_n)},
        "locks": {"installed": _installed,
                  "classes": lock_classes(min_contended=1)[:top_n]},
        "watchdog": _default_watchdog.status(),
        "rss_bytes": _rss_bytes(),
    }


def handle_debug_profile(method: str, q: dict, body: bytes):
    """Shared route handler -> (status, payload, content_type).

    GET  ?format=collapsed      collapsed-stack text (flamegraph wire)
    GET  [?top=N]               JSON: profiler top-N, contended locks,
                                watchdog loops + recent stall events
    POST {"enabled": bool, "hz": f, "reset": bool}   runtime toggle
    """
    prof = _default_profiler
    if method == "POST":
        doc = json.loads(body or b"{}")
        if doc.get("reset"):
            prof.reset()
            reset_lock_stats()
        if "hz" in doc:
            prof.hz = max(0.1, float(doc["hz"]))
        if "enabled" in doc:
            if bool(doc["enabled"]):
                prof.start()
                _default_watchdog.start()
            else:
                prof.enabled = False
        return 200, json.dumps(prof.status()).encode(), "application/json"
    fmt = (q.get("format", [""])[0] if q else "").lower()
    if fmt == "collapsed":
        return 200, prof.collapsed().encode(), "text/plain; charset=utf-8"
    top_n = int(q.get("top", ["20"])[0]) if q else 20
    return (200, json.dumps(profile_payload(top_n)).encode(),
            "application/json")


class DebugServer:
    """Minimal HTTP debug surface for services without one (aggregator,
    kvd): /debug/profile, /metrics, /health. Daemon-threaded."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _do(self, method):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    status, payload, ctype = outer._route(
                        method, u.path, q, body)
                except Exception as e:  # noqa: BLE001 - debug surface
                    status, ctype = 400, "application/json"
                    payload = json.dumps({"error": str(e)}).encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._do("GET")

            def do_POST(self):  # noqa: N802
                self._do("POST")

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         name="debug-http", daemon=True).start()

    def _route(self, method, path, q, body):
        if path == "/debug/profile":
            return handle_debug_profile(method, q, body)
        if path == "/debug/compute":
            from m3_tpu.utils import compute_stats

            return compute_stats.handle_debug_compute(method, q, body)
        if path == "/metrics":
            return (200, default_registry().render_prometheus(),
                    "text/plain; version=0.0.4")
        if path == "/health":
            return 200, b'{"ok":true}', "application/json"
        return 404, b'{"error":"unknown path"}', "application/json"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()  # release the listening socket fd


def serve_debug_from_env() -> DebugServer | None:
    """Start the standalone debug surface when ``M3_TPU_DEBUG_PORT`` is
    set (aggregator/kvd processes; rig arms it). Returns the server (or
    None), never raises — a busy port must not kill a service."""
    raw = os.environ.get("M3_TPU_DEBUG_PORT")
    if not raw:
        return None
    try:
        return DebugServer(port=int(raw))
    except (ValueError, OSError):
        return None
