"""Per-tenant admission control: quotas keyed by namespace.

Role parity with the reference's tenant isolation seams
(/root/reference/src/dbnode/storage/limits — per-query/per-tenant
resource ceilings — and src/x/ratelimit): one hot namespace must degrade
*itself*, never the node. The coordinator consults this controller at
every ingest and query entrypoint (query/api.py):

- **datapoints/sec** and **queries/sec** token buckets per tenant
  (tenant == namespace, the reference's multi-tenancy key);
- a **live series-cardinality ceiling** checked against the storage
  layer's count (storage/limits.live_series) with a TTL cache so the
  hot path never scans shards per write;
- a **query-cost budget** in cost units/sec, charged POST-PAID from the
  finished query's QueryStats counters (series matched + blocks read +
  KiB decoded — the counters every read path already accrues): a tenant
  that just ran an expensive query is shed until its budget refills,
  which is the only honest way to bound cost you cannot know up front.

A shed decision raises :class:`TenantShedError`; the HTTP layer turns it
into ``429`` + ``Retry-After`` (client/breaker.py treats that as
backpressure, never as a breaker failure). Every decision point emits
per-tenant allow/shed counters into the metrics registry and the shed
path carries the ``tenant.admission.shed`` tracepoint — enforced
statically by tools/check_observability.py invariant 5.

Limits are runtime-updatable through the cluster KV (``m3_tpu.tenants``
key, same watch discipline as cluster/runtime.py) so an operator can
throttle a noisy tenant on a LIVE cluster without restarts. The clock is
injectable, so refill/burst/ceiling behavior is unit-testable in virtual
time.
"""

from __future__ import annotations

import json
import math
import threading
import time

# the kvconfig key operators write to retune tenant quotas live
# (reference kvconfig/keys.go discipline; see cluster/runtime.RUNTIME_KEY)
TENANTS_KEY = "m3_tpu.tenants"

# quota fields and their types; 0 means unlimited for every field
_QUOTA_FIELDS = {
    "datapoints_per_sec": float,
    "queries_per_sec": float,
    "max_series": int,
    "query_cost_per_sec": float,
    "burst_s": float,
}


class TenantShedError(Exception):
    """This tenant is over budget: shed THIS request (429), serve the
    rest of the node untouched."""

    def __init__(self, namespace: str, kind: str, retry_after_s: float):
        self.namespace = namespace
        self.kind = kind  # write | query | cardinality | cost
        self.retry_after_s = max(0.001, float(retry_after_s))
        super().__init__(
            f"tenant {namespace!r} over {kind} budget "
            f"(retry after {self.retry_after_s:.3f}s)"
        )


class TenantQuota:
    """One tenant's ceilings; every field 0 = unlimited. Immutable."""

    __slots__ = tuple(_QUOTA_FIELDS)

    def __init__(self, datapoints_per_sec: float = 0.0,
                 queries_per_sec: float = 0.0, max_series: int = 0,
                 query_cost_per_sec: float = 0.0, burst_s: float = 2.0):
        self.datapoints_per_sec = float(datapoints_per_sec)
        self.queries_per_sec = float(queries_per_sec)
        self.max_series = int(max_series)
        self.query_cost_per_sec = float(query_cost_per_sec)
        self.burst_s = float(burst_s)

    def __eq__(self, other):
        return isinstance(other, TenantQuota) and all(
            getattr(self, f) == getattr(other, f) for f in _QUOTA_FIELDS)

    def __repr__(self):
        body = ", ".join(f"{f}={getattr(self, f)!r}" for f in _QUOTA_FIELDS)
        return f"TenantQuota({body})"

    @classmethod
    def from_doc(cls, doc: dict) -> "TenantQuota":
        """Strictly-typed parse (the RuntimeOptions.from_json discipline):
        a mistyped KV payload must fail HERE, visibly, not inside a watch
        listener where errors are swallowed."""
        known = {}
        for k, v in (doc or {}).items():
            want = _QUOTA_FIELDS.get(k)
            if want is None:
                continue  # forward compatibility: ignore unknown keys
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(f"{k} must be a number, got {v!r}")
            known[k] = want(v)
        q = cls(**known)
        if q.burst_s <= 0:
            raise ValueError(f"burst_s must be > 0, got {q.burst_s!r}")
        return q


class TokenBucket:
    """Token bucket on an injectable clock. Supports both pre-paid
    (`try_take` — admission) and post-paid (`charge` — cost budgets)
    accounting; post-paid balances may go negative, which is how a
    single oversized query throttles its tenant's NEXT requests."""

    def __init__(self, rate_per_s: float, burst: float, clock=time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._tokens = self.burst  # start full: boot burst is free
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> float:
        """Take n tokens if available; returns 0.0 on grant, else the
        seconds until the request becomes admittable (the Retry-After).

        A request LARGER than the whole burst capacity could never be
        admitted by waiting (tokens cap at burst), so — like
        cluster/runtime.PersistRateLimiter — it is granted while the
        bucket is solvent, driving the balance negative: the oversized
        batch throttles the tenant's NEXT requests instead of livelocking
        this one behind a Retry-After that can never come true."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            if self.rate <= 0:
                return math.inf
            if n > self.burst:
                if self._tokens >= 0:
                    self._tokens = max(self._tokens - n, -10.0 * self.burst)
                    return 0.0
                return -self._tokens / self.rate  # wait out the debt only
            return (n - self._tokens) / self.rate

    def charge(self, n: float) -> None:
        """Post-paid: subtract n unconditionally. Debt is capped at ten
        bursts so one pathological request cannot lock a tenant out
        forever — it throttles, it does not banish."""
        with self._lock:
            self._refill_locked()
            self._tokens = max(self._tokens - n, -10.0 * self.burst)

    def deficit_s(self) -> float:
        """Seconds until the balance is non-negative (0.0 = solvent)."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= 0:
                return 0.0
            if self.rate <= 0:
                return math.inf
            return -self._tokens / self.rate

    def balance(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens


def query_cost(stats) -> float:
    """Cost units of one finished query, from the QueryStats counters the
    read path already accrues (utils/querystats): series matched + blocks
    read + KiB decoded. Linear and explainable — an operator can derive a
    tenant's budget from the envelope `stats` of their typical queries."""
    if stats is None:
        return 0.0
    return (float(getattr(stats, "series_matched", 0))
            + float(getattr(stats, "blocks_read", 0))
            + float(getattr(stats, "bytes_decoded", 0)) / 1024.0)


class _TenantState:
    """Per-tenant live accounting: one bucket per budgeted dimension,
    lazily built from the quota (None where unlimited)."""

    __slots__ = ("quota", "dp_bucket", "q_bucket", "cost_bucket",
                 "card_at", "card_value")

    def __init__(self, quota: TenantQuota, clock):
        self.quota = quota
        self.dp_bucket = (
            TokenBucket(quota.datapoints_per_sec,
                        quota.datapoints_per_sec * quota.burst_s, clock)
            if quota.datapoints_per_sec > 0 else None)
        self.q_bucket = (
            TokenBucket(quota.queries_per_sec,
                        quota.queries_per_sec * quota.burst_s, clock)
            if quota.queries_per_sec > 0 else None)
        self.cost_bucket = (
            TokenBucket(quota.query_cost_per_sec,
                        quota.query_cost_per_sec * quota.burst_s, clock)
            if quota.query_cost_per_sec > 0 else None)
        self.card_at = -math.inf  # cardinality cache stamp (clock units)
        self.card_value = 0


class TenantAdmission:
    """The per-tenant admission controller the coordinator consults.

    `quotas` maps namespace -> TenantQuota for explicitly configured
    tenants; `default` (optional) applies to every other namespace.
    `cardinality_source(namespace) -> int | None` supplies the live
    series count (None = unknown, e.g. remote cluster storage — the
    ceiling is then not enforced for that namespace)."""

    # bound on lazily-created tenant states: namespaces are operator-
    # created but the ?namespace= value is client-supplied (the same
    # bound discipline as CoordinatorAPI.MAX_ENGINES)
    MAX_TENANTS = 256

    def __init__(self, quotas: dict[str, TenantQuota] | None = None,
                 default: TenantQuota | None = None,
                 clock=time.monotonic, cardinality_source=None,
                 cardinality_ttl_s: float = 1.0):
        from m3_tpu.utils.instrument import default_registry

        self._clock = clock
        self._lock = threading.Lock()
        self._quotas = dict(quotas or {})
        self._default = default
        self._states: dict[str, _TenantState] = {}
        self._cardinality_source = cardinality_source
        self._cardinality_ttl_s = float(cardinality_ttl_s)
        self._scope = default_registry().root_scope("tenant")
        # cached per-(namespace, kind) counters: bounded by MAX_TENANTS x
        # the four shed kinds, and the hot path never rebuilds scopes
        self._counters: dict[tuple[str, str, str], object] = {}
        self._unwatch = None

    # -- configuration surface --

    def known_tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._quotas)

    def has_quota(self, namespace: str) -> bool:
        with self._lock:
            return namespace in self._quotas or self._default is not None

    def is_configured(self, namespace: str) -> bool:
        """True only for EXPLICITLY configured tenants (metric-label
        bounding: default-quota namespaces are client-supplied strings)."""
        with self._lock:
            return namespace in self._quotas

    def set_quotas(self, quotas: dict[str, TenantQuota],
                   default: TenantQuota | None = None) -> None:
        """Swap the whole quota table (the KV watch path). Live bucket
        state is KEPT for tenants whose quota is unchanged — an operator
        tightening tenant A must not hand tenant B a fresh burst — and
        rebuilt (full) where the quota actually changed."""
        with self._lock:
            old_states = self._states
            self._quotas = dict(quotas)
            self._default = default
            self._states = {}
            for ns, st in old_states.items():
                new_q = self._quota_for_locked(ns)
                if new_q is not None and new_q == st.quota:
                    self._states[ns] = st

    def _quota_for_locked(self, namespace: str) -> TenantQuota | None:
        return self._quotas.get(namespace, self._default)

    def _state(self, namespace: str) -> _TenantState | None:
        with self._lock:
            st = self._states.get(namespace)
            if st is not None:
                return st
            quota = self._quota_for_locked(namespace)
            if quota is None:
                return None
            if len(self._states) >= self.MAX_TENANTS:
                # drop an arbitrary non-configured entry (same recycling
                # rule as the engine cache: correctness never depends on
                # accumulated bucket state)
                for key in list(self._states):
                    if key not in self._quotas:
                        del self._states[key]
                        break
            st = self._states[namespace] = _TenantState(quota, self._clock)
            return st

    # -- decision points --

    def _counter(self, namespace: str, verdict: str, kind: str):
        # metric-label bounding: only EXPLICITLY configured tenants get
        # their own label; namespaces admitted via the default quota are
        # client-supplied strings, and a scanner must not be able to
        # grow the registry (or this cache) without bound
        if not self.is_configured(namespace):
            namespace = "other"
        key = (namespace, verdict, kind)
        c = self._counters.get(key)
        if c is None:
            scope = self._scope.subscope("admission", namespace=namespace,
                                         kind=kind)
            c = self._counters[key] = (scope, verdict)
        return c

    def _allow(self, namespace: str, kind: str) -> None:
        scope, verdict = self._counter(namespace, "allowed", kind)
        scope.counter(verdict)

    def _shed(self, namespace: str, kind: str, retry_after_s: float):
        """The shed path: per-tenant counter + tracepoint, then the error
        the HTTP layer maps to 429 + Retry-After."""
        from m3_tpu.utils import trace

        scope, verdict = self._counter(namespace, "shed", kind)
        scope.counter(verdict)
        with trace.span(trace.TENANT_SHED, namespace=namespace, kind=kind,
                        retry_after_s=round(retry_after_s, 3)):
            pass  # the span IS the record: shed decisions join the trace
        raise TenantShedError(namespace, kind, retry_after_s)

    def admit_write(self, namespace: str, datapoints: int) -> None:
        """Gate one ingest batch: cardinality ceiling first (adding load
        to a tenant already over its live-series cap is strictly worse
        than rate-limiting it), then the datapoints/sec bucket."""
        st = self._state(namespace)
        if st is None:
            return  # no quota configured: unlimited
        if st.quota.max_series > 0:
            over = self._cardinality_over(namespace, st)
            if over:
                self._shed(namespace, "cardinality", self._cardinality_ttl_s)
        if st.dp_bucket is not None:
            wait = st.dp_bucket.try_take(float(datapoints))
            if wait > 0:
                self._shed(namespace, "write", wait)
        self._allow(namespace, "write")

    def admit_query(self, namespace: str) -> None:
        """Gate one query: the queries/sec bucket, then the post-paid
        cost budget (a tenant in cost debt is shed until it refills)."""
        st = self._state(namespace)
        if st is None:
            return
        if st.q_bucket is not None:
            wait = st.q_bucket.try_take(1.0)
            if wait > 0:
                self._shed(namespace, "query", wait)
        if st.cost_bucket is not None:
            wait = st.cost_bucket.deficit_s()
            if wait > 0:
                self._shed(namespace, "cost", wait)
        self._allow(namespace, "query")

    def charge_query_cost(self, namespace: str, stats) -> None:
        """Post-paid accounting from the finished query's QueryStats —
        called after the engine ran, never blocks, never raises."""
        st = self._state(namespace)
        if st is None or st.cost_bucket is None:
            return
        st.cost_bucket.charge(query_cost(stats))

    def _cardinality_over(self, namespace: str, st: _TenantState) -> bool:
        now = self._clock()
        if now - st.card_at >= self._cardinality_ttl_s:
            source = self._cardinality_source
            if source is None:
                return False
            try:
                val = source(namespace)
            except Exception:  # noqa: BLE001 - a storage hiccup must not
                return False   # turn the admission path into an outage
            if val is None:
                return False
            st.card_at = now
            st.card_value = int(val)
        return st.card_value >= st.quota.max_series

    # -- KV integration (runtime-updatable limits) --

    def watch_kv(self, kv, key: str = TENANTS_KEY):
        """Follow the tenants KV key; malformed payloads are ignored (the
        runtime.py watch discipline). Returns the unwatch callable."""

        def on_change(_key, vv):
            if vv is None:
                return  # deletion keeps the last applied quotas
            try:
                quotas, default = parse_quota_doc(json.loads(vv.data))
            except (ValueError, TypeError):
                return
            self.set_quotas(quotas, default)

        self._unwatch = kv.watch(key, on_change)
        return self._unwatch


def parse_quota_doc(doc: dict) -> tuple[dict[str, TenantQuota],
                                        TenantQuota | None]:
    """Shared doc shape for the config file `tenants:` section AND the
    `m3_tpu.tenants` KV payload:

        tenants:
          default: {queries_per_sec: 50}
          tenants:
            hot_ns: {datapoints_per_sec: 10000, max_series: 50000}
    """
    if not isinstance(doc, dict):
        raise ValueError(f"tenants doc must be a mapping, got {type(doc)}")
    default = None
    if doc.get("default"):
        default = TenantQuota.from_doc(doc["default"])
    quotas = {}
    for ns, sub in (doc.get("tenants") or {}).items():
        quotas[str(ns)] = TenantQuota.from_doc(sub or {})
    return quotas, default


def from_config(doc: dict | None, clock=time.monotonic,
                cardinality_source=None) -> TenantAdmission | None:
    """Controller from the coordinator config's `tenants:` section; None
    when the section is absent/empty (no controller, zero overhead)."""
    if not doc:
        return None
    quotas, default = parse_quota_doc(doc)
    if not quotas and default is None:
        return None
    return TenantAdmission(quotas, default, clock=clock,
                           cardinality_source=cardinality_source)
