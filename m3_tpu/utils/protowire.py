"""Minimal protobuf wire-format codec + Prometheus remote read/write messages.

Hand-rolled encoders/decoders for the three message shapes the Prometheus
remote APIs need (WriteRequest / ReadRequest / ReadResponse), matching the
public prometheus/prompb schema. The reference carries generated codecs for
the same protocol (/root/reference/src/query/generated/proto/prompb); a
generic field walker keeps this dependency-free.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def iter_fields(data: bytes):
    """Yield (field_number, wire_type, value) over a message payload."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = read_uvarint(data, pos)
        fno, wt = key >> 3, key & 7
        if wt == 0:  # varint
            val, pos = read_uvarint(data, pos)
        elif wt == 1:  # fixed64
            val = data[pos : pos + 8]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = read_uvarint(data, pos)
            val = data[pos : pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            val = data[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, val


def field_varint(fno: int, v: int) -> bytes:
    return _uvarint(fno << 3) + _uvarint(v & ((1 << 64) - 1))


def field_bytes(fno: int, b: bytes) -> bytes:
    return _uvarint((fno << 3) | 2) + _uvarint(len(b)) + b


def field_double(fno: int, v: float) -> bytes:
    return _uvarint((fno << 3) | 1) + struct.pack("<d", v)


# ---------------------------------------------------------------------------
# prometheus remote messages (prompb schema)
# ---------------------------------------------------------------------------


@dataclass
class PromTimeSeries:
    labels: list[tuple[bytes, bytes]] = field(default_factory=list)
    samples: list[tuple[int, float]] = field(default_factory=list)  # (ts_ms, value)


def decode_write_request(payload: bytes) -> list[PromTimeSeries]:
    out = []
    for fno, _, val in iter_fields(payload):
        if fno != 1:
            continue
        ts = PromTimeSeries()
        for f2, _, v2 in iter_fields(val):
            if f2 == 1:  # Label
                name = value = b""
                for f3, _, v3 in iter_fields(v2):
                    if f3 == 1:
                        name = v3
                    elif f3 == 2:
                        value = v3
                ts.labels.append((name, value))
            elif f2 == 2:  # Sample
                value_f = 0.0
                ts_ms = 0
                for f3, wt3, v3 in iter_fields(v2):
                    if f3 == 1:
                        value_f = struct.unpack("<d", v3)[0]
                    elif f3 == 2:
                        # prompb.Sample.timestamp is int64 (not zigzag)
                        ts_ms = v3 if wt3 == 0 else 0
                        if ts_ms >= 1 << 63:
                            ts_ms -= 1 << 64
                ts.samples.append((ts_ms, value_f))
        out.append(ts)
    return out


def encode_write_request(series: list[PromTimeSeries]) -> bytes:
    out = bytearray()
    for ts in series:
        body = bytearray()
        for name, value in ts.labels:
            body += field_bytes(1, field_bytes(1, name) + field_bytes(2, value))
        for ts_ms, v in ts.samples:
            body += field_bytes(2, field_double(1, v) + field_varint(2, ts_ms))
        out += field_bytes(1, bytes(body))
    return bytes(out)


@dataclass
class PromMatcher:
    type: int  # 0 EQ, 1 NEQ, 2 RE, 3 NRE
    name: bytes
    value: bytes


@dataclass
class PromReadQuery:
    start_ms: int
    end_ms: int
    matchers: list[PromMatcher] = field(default_factory=list)


def decode_read_request(payload: bytes) -> list[PromReadQuery]:
    out = []
    for fno, _, val in iter_fields(payload):
        if fno != 1:
            continue
        q = PromReadQuery(0, 0)
        for f2, wt2, v2 in iter_fields(val):
            if f2 == 1 and wt2 == 0:
                q.start_ms = v2
            elif f2 == 2 and wt2 == 0:
                q.end_ms = v2
            elif f2 == 3:
                m = PromMatcher(0, b"", b"")
                for f3, wt3, v3 in iter_fields(v2):
                    if f3 == 1 and wt3 == 0:
                        m.type = v3
                    elif f3 == 2:
                        m.name = v3
                    elif f3 == 3:
                        m.value = v3
                q.matchers.append(m)
        out.append(q)
    return out


def encode_read_request(queries: list[tuple[int, int, list[PromMatcher]]]) -> bytes:
    """Client-side prompb.ReadRequest: [(start_ms, end_ms, matchers)]."""
    out = bytearray()
    for start_ms, end_ms, matchers in queries:
        body = bytearray()
        body += field_varint(1, start_ms)
        body += field_varint(2, end_ms)
        for m in matchers:
            mb = bytearray()
            if m.type:
                mb += field_varint(1, m.type)
            mb += field_bytes(2, m.name)
            mb += field_bytes(3, m.value)
            body += field_bytes(3, bytes(mb))
        out += field_bytes(1, bytes(body))
    return bytes(out)


def decode_read_response(payload: bytes) -> list[list[PromTimeSeries]]:
    """Client-side decode of prompb.ReadResponse (inverse of
    encode_read_response)."""
    results = []
    for fno, _, val in iter_fields(payload):
        if fno != 1:
            continue
        series_list = []
        for f2, _, v2 in iter_fields(val):
            if f2 == 1:
                series_list.extend(decode_write_request(field_bytes(1, v2)))
        results.append(series_list)
    return results


def encode_read_response(results: list[list[PromTimeSeries]]) -> bytes:
    out = bytearray()
    for series_list in results:
        body = bytearray()
        for ts in series_list:
            ts_body = bytearray()
            for name, value in ts.labels:
                ts_body += field_bytes(1, field_bytes(1, name) + field_bytes(2, value))
            for ts_ms, v in ts.samples:
                ts_body += field_bytes(2, field_double(1, v) + field_varint(2, ts_ms))
            body += field_bytes(1, bytes(ts_body))
        out += field_bytes(1, bytes(body))
    return bytes(out)
