"""Murmur3 32-bit hash (public algorithm, Austin Appleby) for shard routing.

The reference routes series to virtual shards with murmur3(id) % n_shards
(/root/reference/src/dbnode/sharding/shardset.go:158-175 and
/root/reference/src/aggregator/sharding/hash.go:37-89); we keep the same
function family so placements stay comparable.
"""

from __future__ import annotations

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    h = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h
