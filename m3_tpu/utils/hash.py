"""Murmur3 32-bit hash (public algorithm, Austin Appleby) for shard routing.

The reference routes series to virtual shards with murmur3(id) % n_shards
(/root/reference/src/dbnode/sharding/shardset.go:158-175 and
/root/reference/src/aggregator/sharding/hash.go:37-89); we keep the same
function family so placements stay comparable.
"""

from __future__ import annotations

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32_batch(ids: list[bytes], seed: int = 0):
    """Vectorized murmur3_32 over a batch of byte strings -> uint32
    ndarray, bit-identical to ``[murmur3_32(x) for x in ids]``.

    The per-id Python loop collapses to one buffer concatenation; the
    hash itself runs as numpy ops over a padded [n, max_len] byte matrix
    with per-row active masks (rows shorter than the current block keep
    their prior h). Arithmetic is uint64 masked back to 32 bits after
    every op so the wraparound semantics match the scalar path exactly.
    Worth it from a few hundred ids (read_many's series->shard routing
    hashes 10k+ ids per call)."""
    import numpy as np

    n = len(ids)
    if n == 0:
        return np.empty(0, np.uint32)
    lengths = np.fromiter((len(s) for s in ids), np.int64, count=n)
    max_len = int(lengths.max())
    m32 = np.uint64(_M32)
    h = np.full(n, seed & _M32, np.uint64)
    if max_len:
        flat = np.frombuffer(b"".join(ids), np.uint8)
        offsets = np.concatenate([[0], np.cumsum(lengths)[:-1]])
        idx = offsets[:, None] + np.arange(max_len)
        padded = np.where(np.arange(max_len) < lengths[:, None],
                          flat[np.minimum(idx, len(flat) - 1)],
                          0).astype(np.uint64)
        nblocks = lengths // 4
        c1, c2 = np.uint64(_C1), np.uint64(_C2)
        for i in range(max_len // 4):
            k = (padded[:, 4 * i]
                 | padded[:, 4 * i + 1] << np.uint64(8)
                 | padded[:, 4 * i + 2] << np.uint64(16)
                 | padded[:, 4 * i + 3] << np.uint64(24))
            k = k * c1 & m32
            k = (k << np.uint64(15) | k >> np.uint64(17)) & m32
            k = k * c2 & m32
            hh = h ^ k
            hh = (hh << np.uint64(13) | hh >> np.uint64(19)) & m32
            hh = (hh * np.uint64(5) + np.uint64(0xE6546B64)) & m32
            h = np.where(i < nblocks, hh, h)
        tail_len = lengths - nblocks * 4
        if tail_len.any():
            base = nblocks * 4
            cols = np.minimum(base[:, None] + np.arange(3), max_len - 1)
            tail = np.take_along_axis(padded, cols, axis=1)
            k = np.zeros(n, np.uint64)
            k = np.where(tail_len >= 3, k ^ tail[:, 2] << np.uint64(16), k)
            k = np.where(tail_len >= 2, k ^ tail[:, 1] << np.uint64(8), k)
            k ^= np.where(tail_len >= 1, tail[:, 0], 0)
            k = k * c1 & m32
            k = (k << np.uint64(15) | k >> np.uint64(17)) & m32
            k = k * c2 & m32
            h = np.where(tail_len >= 1, h ^ k, h)
    h ^= lengths.astype(np.uint64)
    h ^= h >> np.uint64(16)
    h = h * np.uint64(0x85EBCA6B) & m32
    h ^= h >> np.uint64(13)
    h = h * np.uint64(0xC2B2AE35) & m32
    h ^= h >> np.uint64(16)
    return h.astype(np.uint32)


def murmur3_32(data: bytes, seed: int = 0) -> int:
    h = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _M32
        k = _rotl32(k, 15)
        k = (k * _C2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h
