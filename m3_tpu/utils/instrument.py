"""Instrumentation: metrics scopes + structured logging.

Role parity with the reference's x/instrument (tally scopes + zap logging):
a process-local metrics registry with counters/gauges/timers and tagged
subscopes, exportable in Prometheus text format (served on /metrics by the
services), plus a minimal structured logger. The platform monitors itself
with the same metric model it stores.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import defaultdict
from dataclasses import dataclass


@dataclass
class _Counter:
    value: float = 0.0


@dataclass
class _Gauge:
    value: float = 0.0


@dataclass
class _Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0


class Scope:
    """Tagged metrics scope; subscope() adds tags, prefix joins with '.'"""

    def __init__(self, registry: "MetricsRegistry", prefix: str = "",
                 tags: tuple = ()):
        self._registry = registry
        self._prefix = prefix
        self._tags = tags

    def _name(self, name: str) -> str:
        return f"{self._prefix}.{name}" if self._prefix else name

    def subscope(self, prefix: str, **tags) -> "Scope":
        merged = tuple(sorted({**dict(self._tags), **tags}.items()))
        return Scope(self._registry, self._name(prefix), merged)

    def counter(self, name: str, delta: float = 1.0) -> None:
        with self._registry._lock:
            self._registry.counters[(self._name(name), self._tags)].value += delta

    def gauge(self, name: str, value: float) -> None:
        with self._registry._lock:
            self._registry.gauges[(self._name(name), self._tags)].value = value

    def timer(self, name: str):
        """Context manager recording a duration."""
        scope = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                dt = time.perf_counter() - self.t0
                with scope._registry._lock:
                    t = scope._registry.timers[(scope._name(name), scope._tags)]
                    t.count += 1
                    t.total_s += dt
                    t.max_s = max(t.max_s, dt)

        return _Ctx()


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict = defaultdict(_Counter)
        self.gauges: dict = defaultdict(_Gauge)
        self.timers: dict = defaultdict(_Timer)

    def root_scope(self, prefix: str = "") -> Scope:
        return Scope(self, prefix)

    def render_prometheus(self) -> bytes:
        """Prometheus text exposition of everything recorded."""
        out = []

        def fmt(name, tags, value):
            name = name.replace(".", "_").replace("-", "_")
            if tags:
                t = ",".join(f'{k}="{v}"' for k, v in tags)
                out.append(f"{name}{{{t}}} {value}")
            else:
                out.append(f"{name} {value}")

        with self._lock:
            for (name, tags), c in sorted(self.counters.items()):
                fmt(name, tags, c.value)
            for (name, tags), g in sorted(self.gauges.items()):
                fmt(name, tags, g.value)
            for (name, tags), t in sorted(self.timers.items()):
                fmt(name + "_count", tags, t.count)
                fmt(name + "_total_seconds", tags, round(t.total_s, 9))
                fmt(name + "_max_seconds", tags, round(t.max_s, 9))
        return ("\n".join(out) + "\n").encode()


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


class Logger:
    """Structured JSON-lines logger (the zap role)."""

    LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

    def __init__(self, name: str = "", level: str = "info", stream=None):
        self.name = name
        self.level = self.LEVELS[level]
        self.stream = stream if stream is not None else sys.stderr
        self.fields: dict = {}

    def with_fields(self, **fields) -> "Logger":
        lg = Logger(self.name, stream=self.stream)
        lg.level = self.level
        lg.fields = {**self.fields, **fields}
        return lg

    def _log(self, level: str, msg: str, **fields) -> None:
        if self.LEVELS[level] < self.level:
            return
        rec = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "msg": msg,
            **self.fields,
            **fields,
        }
        print(json.dumps(rec, default=str), file=self.stream, flush=True)

    def debug(self, msg: str, **fields) -> None:
        self._log("debug", msg, **fields)

    def info(self, msg: str, **fields) -> None:
        self._log("info", msg, **fields)

    def warn(self, msg: str, **fields) -> None:
        self._log("warn", msg, **fields)

    def error(self, msg: str, **fields) -> None:
        self._log("error", msg, **fields)
