"""Instrumentation: metrics scopes + structured logging.

Role parity with the reference's x/instrument (tally scopes + zap logging):
a process-local metrics registry with counters/gauges/timers/histograms and
tagged subscopes, exportable in strict Prometheus text format (served on
/metrics by the services, `# TYPE` metadata + escaped labels + safe
NaN/Inf), plus a minimal structured logger. The platform monitors itself
with the same metric model it stores: the coordinator's self-scrape loop
(utils/selfscrape.py) ingests this registry into the `_m3_system`
namespace so p99s over these histograms are one PromQL query away.

Exemplars: every histogram observation made inside a SAMPLED trace pins a
``(trace_id, value, timestamp)`` exemplar to the bucket it landed in —
last observation wins per bucket, so each bucket of a latency histogram
always points at a recent representative trace. The OpenMetrics-style render
(``render_openmetrics``, served on ``/metrics?format=openmetrics`` —
explicit opt-in only) emits them as
``# {trace_id="..."} value ts`` suffixes on `_bucket` lines, so a p99
bucket is one /debug/traces lookup away from its stitched trace. The
plain Prometheus render is byte-compatible with PR 4 (no exemplars —
that format has no syntax for them).
"""

from __future__ import annotations

import bisect
import json
import math
import sys
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class _Counter:
    value: float = 0.0


@dataclass
class _Gauge:
    value: float = 0.0


@dataclass
class _Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0


# log-bucketed histogram bounds: powers of two from ~1us to ~64s — 14
# buckets per 1000x decade, enough that p99 interpolation error stays
# under ~2x anywhere in the range while one histogram costs ~30 ints
DEFAULT_BUCKETS: tuple = tuple(2.0 ** e for e in range(-20, 7))
# bounds for COUNT-shaped distributions (batch sizes, fan-out widths):
# powers of two from 1 to ~1M
COUNT_BUCKETS: tuple = tuple(float(2 ** e) for e in range(0, 21))


# bound lazily (first traced observation), then a straight thread-local
# read per observation: an in-function `import` here costs ~2us per call,
# which at per-datapoint seam frequency is the difference between
# exemplars being free and blowing the bench-#7 overhead guard
_tracer_tl = None


def _active_exemplar_trace() -> str | None:
    """The trace id an observation should pin as its exemplar: the
    thread's active SAMPLED span context, or None outside a recorded
    trace (one thread-local read — the histogram hot paths call this per
    observation)."""
    global _tracer_tl
    if _tracer_tl is None:
        from m3_tpu.utils import trace

        _tracer_tl = trace.default_tracer()._tl
    ctx = getattr(_tracer_tl, "ctx", None)
    if ctx is None or not ctx.sampled or not ctx.span_id:
        return None
    return ctx.trace_id


@dataclass
class _Histogram:
    bounds: tuple = DEFAULT_BUCKETS
    counts: list = field(default_factory=lambda: [0] * (len(DEFAULT_BUCKETS) + 1))
    sum: float = 0.0
    count: int = 0
    # per-bucket (trace_id, value, unix_seconds) exemplar, last-wins;
    # allocated on the first traced observation so untraced histograms
    # stay three scalars + a counts list
    exemplars: list | None = None

    def observe_locked(self, value: float,
                       exemplar_trace: str | None = None) -> None:
        """Record one observation; caller holds the registry lock."""
        i = bisect.bisect_left(self.bounds, value)
        self.counts[i] += 1
        self.sum += value
        self.count += 1
        if exemplar_trace is not None:
            if self.exemplars is None:
                self.exemplars = [None] * len(self.counts)
            self.exemplars[i] = (exemplar_trace, value, time.time())

    def cumulative(self) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)] incl. the +Inf bucket."""
        out = []
        running = 0
        for ub, c in zip(self.bounds, self.counts):
            running += c
            out.append((ub, running))
        out.append((math.inf, running + self.counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Interpolated quantile (the histogram_quantile rule) — used by
        in-process consumers (slow-query thresholds, tests)."""
        if self.count == 0:
            return math.nan
        rank = q * self.count
        running = 0
        prev_ub = 0.0
        for ub, c in zip(self.bounds, self.counts):
            if running + c >= rank:
                if c == 0:
                    return ub
                return prev_ub + (ub - prev_ub) * (rank - running) / c
            running += c
            prev_ub = ub
        return self.bounds[-1]


class Scope:
    """Tagged metrics scope; subscope() adds tags, prefix joins with '.'"""

    def __init__(self, registry: "MetricsRegistry", prefix: str = "",
                 tags: tuple = ()):
        self._registry = registry
        self._prefix = prefix
        self._tags = tags

    def _name(self, name: str) -> str:
        return f"{self._prefix}.{name}" if self._prefix else name

    def subscope(self, prefix: str, **tags) -> "Scope":
        merged = tuple(sorted({**dict(self._tags), **tags}.items()))
        return Scope(self._registry, self._name(prefix), merged)

    def counter(self, name: str, delta: float = 1.0) -> None:
        with self._registry._lock:
            self._registry.counters[(self._name(name), self._tags)].value += delta

    def gauge(self, name: str, value: float) -> None:
        with self._registry._lock:
            self._registry.gauges[(self._name(name), self._tags)].value = value

    def timer(self, name: str):
        """Context manager recording a duration."""
        scope = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                dt = time.perf_counter() - self.t0
                with scope._registry._lock:
                    t = scope._registry.timers[(scope._name(name), scope._tags)]
                    t.count += 1
                    t.total_s += dt
                    t.max_s = max(t.max_s, dt)

        return _Ctx()

    def _histogram_locked(self, name: str, bounds: tuple | None):
        """Get-or-create under the registry lock; `bounds` only applies on
        creation (first binding wins, like Prometheus client libs)."""
        reg = self._registry
        key = (self._name(name), self._tags)
        h = reg.histograms.get(key)
        if h is None:
            h = _Histogram(bounds=tuple(bounds)) if bounds else _Histogram()
            if bounds:
                h.counts = [0] * (len(h.bounds) + 1)
            reg.histograms[key] = h
        return h

    def observe(self, name: str, value: float,
                bounds: tuple | None = None) -> None:
        """One histogram observation (seconds for latency seams; pass
        COUNT_BUCKETS bounds for size-shaped distributions). Unlike a
        timer, the distribution survives: p50/p99 are derivable from the
        `_bucket` exposition instead of only count/total/max. Observed
        inside a sampled trace, the bucket pins a (trace_id, value)
        exemplar."""
        ex = _active_exemplar_trace()
        with self._registry._lock:
            self._histogram_locked(name, bounds).observe_locked(value, ex)

    def histogram(self, name: str):
        """Context manager observing a duration into the histogram."""
        scope = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                scope.observe(name, time.perf_counter() - self.t0)

        return _Ctx()

    def histogram_handle(self, name: str, bounds: tuple | None = None):
        """Pre-resolved observe(value) callable for HOT paths: the metric
        key is built once here and the closure binds everything it touches,
        so each observation is a bisect (outside the lock — bounds are
        immutable) plus three adds under a bare acquire/release. Scope
        .observe rebuilds the key string and enters a context manager per
        call — measurably slower on per-datapoint seams. Exemplar-capable
        like observe: a sampled trace context pins its trace_id to the
        bucket (one thread-local read when no trace is active)."""
        from m3_tpu.utils import trace

        reg = self._registry
        with reg._lock:
            h = self._histogram_locked(name, bounds)
        acquire = reg._lock.acquire
        release = reg._lock.release
        h_bounds = h.bounds
        counts = h.counts
        _bisect = bisect.bisect_left
        # the tracer's raw thread-local, read inline (no function call):
        # per-datapoint seams pay one getattr for exemplar capability
        tracer_tl = trace.default_tracer()._tl
        _getattr = getattr
        _now = time.time

        def observe(value: float) -> None:
            i = _bisect(h_bounds, value)
            ctx = _getattr(tracer_tl, "ctx", None)
            acquire()
            counts[i] += 1
            h.sum += value
            h.count += 1
            if ctx is not None and ctx.sampled and ctx.span_id:
                if h.exemplars is None:
                    h.exemplars = [None] * len(counts)
                h.exemplars[i] = (ctx.trace_id, value, _now())
            release()

        return observe


# ---------------------------------------------------------------------------
# snapshot hooks + bounded-queue saturation monitors
# ---------------------------------------------------------------------------

# hooks run at the top of every MetricsRegistry.snapshot() — the one
# choke point every consumer (the /metrics render, the telemetry
# exporter, the _m3_system self-scrape) already goes through — so
# pull-model telemetry (queue depths, lock-wait deltas) is always fresh
# at read time without its own refresh loops. Guarded against
# re-entrancy: a hook that snapshots a registry runs with hooks off.
_hooks_lock = threading.Lock()
_snapshot_hooks: list = []
_hooks_tl = threading.local()


def register_snapshot_hook(fn) -> None:
    """Register fn(registry) to run before every registry snapshot."""
    with _hooks_lock:
        if fn not in _snapshot_hooks:
            _snapshot_hooks.append(fn)


def _run_snapshot_hooks(registry: "MetricsRegistry") -> None:
    if getattr(_hooks_tl, "running", False):
        return
    _hooks_tl.running = True
    try:
        _refresh_queue_monitors(registry)
        with _hooks_lock:
            hooks = list(_snapshot_hooks)
        for fn in hooks:
            try:
                fn(registry)
            except Exception:  # noqa: BLE001 - telemetry hooks must never
                pass           # break a scrape
    finally:
        _hooks_tl.running = False


class _MonitorFns:
    """The callables of one registration. With an `owner`, the STRONG
    reference to this holder lives on the owner object itself and the
    registry keeps only a weakref — the registered closures almost
    always close over the owner, so holding them strongly here would pin
    an abandoned owner (and its buffers/sockets) for process lifetime.
    Owner + holder + closures form a cycle; the gc collects it whole,
    the weakref dies, and the monitor prunes itself."""

    __slots__ = ("depth_fn", "capacity", "drops_fn", "__weakref__")

    def __init__(self, depth_fn, capacity, drops_fn):
        self.depth_fn = depth_fn
        self.capacity = capacity
        self.drops_fn = drops_fn


class _QueueMonitor:
    __slots__ = ("name", "tags", "fns_ref", "registry")

    def __init__(self, name, tags, fns_ref, registry):
        self.name = name
        self.tags = tags
        self.fns_ref = fns_ref  # () -> _MonitorFns | None (None = dead)
        self.registry = registry


_monitors_lock = threading.Lock()
_queue_monitors: list[_QueueMonitor] = []


def monitor_queue(name: str, depth_fn, capacity=None, drops_fn=None,
                  registry: "MetricsRegistry | None" = None, owner=None,
                  **tags):
    """Register a bounded queue/ring with the saturation plane: its
    depth/capacity/drop gauges (``queue_depth{queue=...}`` etc.) refresh
    at every registry snapshot, so /metrics, the exporter and the
    ``_m3_system`` self-scrape all see saturation without the queue
    owner pushing anything. `capacity` is an int or a callable;
    `drops_fn` (optional) reads a monotonic dropped-items counter.
    Passing `owner` ties the registration's lifetime to that object:
    the callables are anchored ON the owner and the registry keeps only
    a weakref, so an owner dropped without close() is still collectable
    (closures over `self` would otherwise pin it here forever) and its
    monitor prunes itself at the next refresh. Returns an unregister
    callable. m3lint's ``inv-queue-gauge`` invariant holds every bounded
    queue in the tree to this registration."""
    import weakref

    fns = _MonitorFns(depth_fn, capacity, drops_fn)
    if owner is not None:
        anchors = getattr(owner, "_m3_monitor_fns", None)
        if anchors is None:
            anchors = []
            try:
                owner._m3_monitor_fns = anchors
            except AttributeError:  # __slots__ owner: fall back to a
                anchors = None      # strong (immortal) registration
        if anchors is not None:
            anchors.append(fns)
            fns_ref = weakref.ref(fns)
        else:
            fns_ref = (lambda f=fns: f)
    else:
        fns_ref = (lambda f=fns: f)
    mon = _QueueMonitor(name, tuple(sorted(tags.items())), fns_ref, registry)
    with _monitors_lock:
        _queue_monitors.append(mon)

    def unregister():
        with _monitors_lock:
            try:
                _queue_monitors.remove(mon)
            except ValueError:
                pass

    return unregister


def _refresh_queue_monitors(registry: "MetricsRegistry") -> None:
    dead: list[_QueueMonitor] = []
    with _monitors_lock:
        monitors = list(_queue_monitors)
    for mon in monitors:
        target = mon.registry if mon.registry is not None \
            else _default_registry
        if target is not registry:
            continue
        fns = mon.fns_ref()
        if fns is None:  # owner (and its anchored callables) collected
            dead.append(mon)
            continue
        try:
            depth = float(fns.depth_fn())
            cap = fns.capacity() if callable(fns.capacity) else fns.capacity
            drops = float(fns.drops_fn()) if fns.drops_fn is not None else None
        except Exception:  # noqa: BLE001 - a mid-teardown queue must not
            continue       # break the scrape
        scope = Scope(registry, "queue",
                      tuple(sorted((("queue", mon.name), *mon.tags))))
        scope.gauge("depth", depth)
        if cap is not None:
            scope.gauge("capacity", float(cap))
        if drops is not None:
            scope.gauge("dropped", drops)
    if dead:
        with _monitors_lock:
            for mon in dead:
                try:
                    _queue_monitors.remove(mon)
                except ValueError:
                    pass


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _escape_label(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_number(value) -> str:
    """Exposition-safe value: NaN / +Inf / -Inf tokens, floats via repr."""
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(v)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict = defaultdict(_Counter)
        self.gauges: dict = defaultdict(_Gauge)
        self.timers: dict = defaultdict(_Timer)
        self.histograms: dict = defaultdict(_Histogram)

    def root_scope(self, prefix: str = "") -> Scope:
        return Scope(self, prefix)

    def merge_histogram(self, name: str, tags: tuple, bounds: tuple,
                        counts_delta, sum_delta: float) -> None:
        """Fold externally-accumulated histogram DELTAS into this
        registry (the lock-wait profiler publishes through here: its hot
        path must not touch the registry lock, so it accumulates raw and
        merges at snapshot time). First merge binds the bounds."""
        with self._lock:
            key = (name, tags)
            h = self.histograms.get(key)
            if h is None:
                h = _Histogram(bounds=tuple(bounds))
                h.counts = [0] * (len(h.bounds) + 1)
                self.histograms[key] = h
            for i, c in enumerate(counts_delta):
                if i < len(h.counts):
                    h.counts[i] += c
            h.sum += sum_delta
            h.count += sum(counts_delta)

    def snapshot(self):
        """Point-in-time copy of every metric, one lock acquisition:
        (counters, gauges, timers, histograms) dicts keyed (name, tags).
        Histogram entries are (bounds, counts, sum, count) tuples.
        Registered snapshot hooks (queue-saturation gauges, lock-wait
        publishing) run first, so every consumer reads fresh values."""
        _run_snapshot_hooks(self)
        with self._lock:
            counters = {k: c.value for k, c in self.counters.items()}
            gauges = {k: g.value for k, g in self.gauges.items()}
            timers = {k: (t.count, t.total_s, t.max_s)
                      for k, t in self.timers.items()}
            hists = {k: (h.bounds, list(h.counts), h.sum, h.count)
                     for k, h in self.histograms.items()}
        return counters, gauges, timers, hists

    def render_prometheus(self) -> bytes:
        """Strict Prometheus text exposition: `# TYPE` metadata per family,
        escaped label values, NaN/±Inf rendered as exposition tokens, and
        histograms as cumulative `_bucket`/`_sum`/`_count` series. The
        device-dispatch counters (utils/dispatch) are merged in so the
        XLA / native / scalar path choice is visible on /metrics."""
        out: list[str] = []
        typed: set[str] = set()

        def fmt(name, tags, value, mtype=None):
            name = _prom_name(name)
            if mtype is not None and name not in typed:
                typed.add(name)
                out.append(f"# TYPE {name} {mtype}")
            if tags:
                t = ",".join(f'{k}="{_escape_label(v)}"' for k, v in tags)
                out.append(f"{name}{{{t}}} {_fmt_number(value)}")
            else:
                out.append(f"{name} {_fmt_number(value)}")

        counters, gauges, timers, hists = self.snapshot()
        for (name, tags), v in sorted(counters.items()):
            fmt(name, tags, v, "counter")
        for (name, tags), v in sorted(gauges.items()):
            fmt(name, tags, v, "gauge")
        for (name, tags), (count, total_s, max_s) in sorted(timers.items()):
            fmt(name + "_count", tags, count, "counter")
            fmt(name + "_total_seconds", tags, round(total_s, 9), "counter")
            fmt(name + "_max_seconds", tags, round(max_s, 9), "gauge")
        for (name, tags), (bounds, counts, hsum, hcount) in sorted(hists.items()):
            h = _Histogram(bounds, counts, hsum, hcount)
            base = _prom_name(name)
            if base not in typed:
                typed.add(base)
                out.append(f"# TYPE {base} histogram")
            for ub, cum in h.cumulative():
                le = "+Inf" if math.isinf(ub) else _fmt_number(ub)
                fmt(name + "_bucket", (*tags, ("le", le)), cum)
            fmt(name + "_sum", tags, round(hsum, 9))
            fmt(name + "_count", tags, hcount)
        # device-dispatch path counters ("op" or "op[path]" keys)
        try:
            from m3_tpu.utils import dispatch

            items = sorted(dispatch.counters.items())
        except Exception:  # noqa: BLE001 - never break /metrics
            items = []
        for key, v in items:
            op, _, path = key.partition("[")
            tags = (("op", op),)
            if path:
                tags += (("path", path.rstrip("]")),)
            fmt("m3_dispatch_ops_total", tags, v, "counter")
        return ("\n".join(out) + "\n").encode()

    def render_openmetrics(self) -> bytes:
        """OpenMetrics-style text exposition: the Prometheus render plus
        histogram-bucket EXEMPLARS (`# {trace_id="..."} value ts` suffix
        per the OpenMetrics exemplar syntax) and the `# EOF` terminator.
        Served only on explicit opt-in (`/metrics?format=openmetrics`):
        family names match the Prometheus render exactly (counters keep
        their PR-4 names rather than gaining the `_total` suffix strict
        OpenMetrics mandates) so dashboards and the `_m3_system`
        self-scrape series line up across both formats — which is also
        why this render must never be Accept-negotiated to a stock
        scraper expecting spec-strict OpenMetrics."""
        # exemplars are not in snapshot() (its consumers - selfscrape,
        # the prometheus render - have no use for them), so take one
        # dedicated locked pass here, capturing bounds alongside
        with self._lock:
            exemplars = {}
            bounds_of = {}
            for k, h in self.histograms.items():
                if h.exemplars:
                    exemplars[k] = list(h.exemplars)
                    bounds_of[k] = h.bounds
        # per rendered-line prefix (`name_bucket{tags,le="..."` — the exact
        # string render_prometheus emits before the space): the exemplar
        # pinned to that bucket. Tags participate in the key, so two
        # histograms sharing a family name cannot cross-pollinate.
        by_prefix: dict[str, tuple] = {}
        for (name, tags), ex in exemplars.items():
            bounds = bounds_of[(name, tags)]
            tag_str = ",".join(f'{k}="{_escape_label(v)}"' for k, v in tags)
            for slot, pinned in enumerate(ex):
                if pinned is None:
                    continue
                le = "+Inf" if slot >= len(bounds) \
                    else _fmt_number(bounds[slot])
                labels = (tag_str + "," if tag_str else "") + f'le="{le}"'
                by_prefix[f"{_prom_name(name)}_bucket{{{labels}}}"] = pinned
        base = self.render_prometheus().decode()
        out: list[str] = []
        for line in base.splitlines():
            brace = line.find("{")
            pinned = by_prefix.get(line[: line.rfind(" ")]) \
                if brace > 0 else None
            if pinned is not None:
                trace_id, value, ts = pinned
                line = (f'{line} # {{trace_id="{_escape_label(trace_id)}"}} '
                        f"{_fmt_number(value)} {ts:.3f}")
            out.append(line)
        return ("\n".join(out) + "\n# EOF\n").encode()


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


class Logger:
    """Structured JSON-lines logger (the zap role)."""

    LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

    def __init__(self, name: str = "", level: str = "info", stream=None):
        self.name = name
        self.level = self.LEVELS[level]
        self.stream = stream if stream is not None else sys.stderr
        self.fields: dict = {}

    def with_fields(self, **fields) -> "Logger":
        lg = Logger(self.name, stream=self.stream)
        lg.level = self.level
        lg.fields = {**self.fields, **fields}
        return lg

    def _log(self, level: str, msg: str, **fields) -> None:
        if self.LEVELS[level] < self.level:
            return
        rec = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "msg": msg,
            **self.fields,
            **fields,
        }
        print(json.dumps(rec, default=str), file=self.stream, flush=True)

    def debug(self, msg: str, **fields) -> None:
        self._log("debug", msg, **fields)

    def info(self, msg: str, **fields) -> None:
        self._log("info", msg, **fields)

    def warn(self, msg: str, **fields) -> None:
        self._log("warn", msg, **fields)

    def error(self, msg: str, **fields) -> None:
        self._log("error", msg, **fields)
