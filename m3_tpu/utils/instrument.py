"""Instrumentation: metrics scopes + structured logging.

Role parity with the reference's x/instrument (tally scopes + zap logging):
a process-local metrics registry with counters/gauges/timers/histograms and
tagged subscopes, exportable in strict Prometheus text format (served on
/metrics by the services, `# TYPE` metadata + escaped labels + safe
NaN/Inf), plus a minimal structured logger. The platform monitors itself
with the same metric model it stores: the coordinator's self-scrape loop
(utils/selfscrape.py) ingests this registry into the `_m3_system`
namespace so p99s over these histograms are one PromQL query away.
"""

from __future__ import annotations

import bisect
import json
import math
import sys
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class _Counter:
    value: float = 0.0


@dataclass
class _Gauge:
    value: float = 0.0


@dataclass
class _Timer:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0


# log-bucketed histogram bounds: powers of two from ~1us to ~64s — 14
# buckets per 1000x decade, enough that p99 interpolation error stays
# under ~2x anywhere in the range while one histogram costs ~30 ints
DEFAULT_BUCKETS: tuple = tuple(2.0 ** e for e in range(-20, 7))


@dataclass
class _Histogram:
    bounds: tuple = DEFAULT_BUCKETS
    counts: list = field(default_factory=lambda: [0] * (len(DEFAULT_BUCKETS) + 1))
    sum: float = 0.0
    count: int = 0

    def observe_locked(self, value: float) -> None:
        """Record one observation; caller holds the registry lock."""
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)] incl. the +Inf bucket."""
        out = []
        running = 0
        for ub, c in zip(self.bounds, self.counts):
            running += c
            out.append((ub, running))
        out.append((math.inf, running + self.counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Interpolated quantile (the histogram_quantile rule) — used by
        in-process consumers (slow-query thresholds, tests)."""
        if self.count == 0:
            return math.nan
        rank = q * self.count
        running = 0
        prev_ub = 0.0
        for ub, c in zip(self.bounds, self.counts):
            if running + c >= rank:
                if c == 0:
                    return ub
                return prev_ub + (ub - prev_ub) * (rank - running) / c
            running += c
            prev_ub = ub
        return self.bounds[-1]


class Scope:
    """Tagged metrics scope; subscope() adds tags, prefix joins with '.'"""

    def __init__(self, registry: "MetricsRegistry", prefix: str = "",
                 tags: tuple = ()):
        self._registry = registry
        self._prefix = prefix
        self._tags = tags

    def _name(self, name: str) -> str:
        return f"{self._prefix}.{name}" if self._prefix else name

    def subscope(self, prefix: str, **tags) -> "Scope":
        merged = tuple(sorted({**dict(self._tags), **tags}.items()))
        return Scope(self._registry, self._name(prefix), merged)

    def counter(self, name: str, delta: float = 1.0) -> None:
        with self._registry._lock:
            self._registry.counters[(self._name(name), self._tags)].value += delta

    def gauge(self, name: str, value: float) -> None:
        with self._registry._lock:
            self._registry.gauges[(self._name(name), self._tags)].value = value

    def timer(self, name: str):
        """Context manager recording a duration."""
        scope = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                dt = time.perf_counter() - self.t0
                with scope._registry._lock:
                    t = scope._registry.timers[(scope._name(name), scope._tags)]
                    t.count += 1
                    t.total_s += dt
                    t.max_s = max(t.max_s, dt)

        return _Ctx()

    def observe(self, name: str, value: float) -> None:
        """One histogram observation (seconds for latency seams). Unlike a
        timer, the distribution survives: p50/p99 are derivable from the
        `_bucket` exposition instead of only count/total/max."""
        with self._registry._lock:
            self._registry.histograms[(self._name(name), self._tags)] \
                .observe_locked(value)

    def histogram(self, name: str):
        """Context manager observing a duration into the histogram."""
        scope = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                scope.observe(name, time.perf_counter() - self.t0)

        return _Ctx()

    def histogram_handle(self, name: str):
        """Pre-resolved observe(value) callable for HOT paths: the metric
        key is built once here and the closure binds everything it touches,
        so each observation is a bisect (outside the lock — bounds are
        immutable) plus three adds under a bare acquire/release. Scope
        .observe rebuilds the key string and enters a context manager per
        call — measurably slower on per-datapoint seams."""
        reg = self._registry
        with reg._lock:
            h = reg.histograms[(self._name(name), self._tags)]
        acquire = reg._lock.acquire
        release = reg._lock.release
        bounds = h.bounds
        counts = h.counts
        _bisect = bisect.bisect_left

        def observe(value: float) -> None:
            i = _bisect(bounds, value)
            acquire()
            counts[i] += 1
            h.sum += value
            h.count += 1
            release()

        return observe


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _escape_label(v) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_number(value) -> str:
    """Exposition-safe value: NaN / +Inf / -Inf tokens, floats via repr."""
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(v)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: dict = defaultdict(_Counter)
        self.gauges: dict = defaultdict(_Gauge)
        self.timers: dict = defaultdict(_Timer)
        self.histograms: dict = defaultdict(_Histogram)

    def root_scope(self, prefix: str = "") -> Scope:
        return Scope(self, prefix)

    def snapshot(self):
        """Point-in-time copy of every metric, one lock acquisition:
        (counters, gauges, timers, histograms) dicts keyed (name, tags).
        Histogram entries are (bounds, counts, sum, count) tuples."""
        with self._lock:
            counters = {k: c.value for k, c in self.counters.items()}
            gauges = {k: g.value for k, g in self.gauges.items()}
            timers = {k: (t.count, t.total_s, t.max_s)
                      for k, t in self.timers.items()}
            hists = {k: (h.bounds, list(h.counts), h.sum, h.count)
                     for k, h in self.histograms.items()}
        return counters, gauges, timers, hists

    def render_prometheus(self) -> bytes:
        """Strict Prometheus text exposition: `# TYPE` metadata per family,
        escaped label values, NaN/±Inf rendered as exposition tokens, and
        histograms as cumulative `_bucket`/`_sum`/`_count` series. The
        device-dispatch counters (utils/dispatch) are merged in so the
        XLA / native / scalar path choice is visible on /metrics."""
        out: list[str] = []
        typed: set[str] = set()

        def fmt(name, tags, value, mtype=None):
            name = _prom_name(name)
            if mtype is not None and name not in typed:
                typed.add(name)
                out.append(f"# TYPE {name} {mtype}")
            if tags:
                t = ",".join(f'{k}="{_escape_label(v)}"' for k, v in tags)
                out.append(f"{name}{{{t}}} {_fmt_number(value)}")
            else:
                out.append(f"{name} {_fmt_number(value)}")

        counters, gauges, timers, hists = self.snapshot()
        for (name, tags), v in sorted(counters.items()):
            fmt(name, tags, v, "counter")
        for (name, tags), v in sorted(gauges.items()):
            fmt(name, tags, v, "gauge")
        for (name, tags), (count, total_s, max_s) in sorted(timers.items()):
            fmt(name + "_count", tags, count, "counter")
            fmt(name + "_total_seconds", tags, round(total_s, 9), "counter")
            fmt(name + "_max_seconds", tags, round(max_s, 9), "gauge")
        for (name, tags), (bounds, counts, hsum, hcount) in sorted(hists.items()):
            h = _Histogram(bounds, counts, hsum, hcount)
            base = _prom_name(name)
            if base not in typed:
                typed.add(base)
                out.append(f"# TYPE {base} histogram")
            for ub, cum in h.cumulative():
                le = "+Inf" if math.isinf(ub) else _fmt_number(ub)
                fmt(name + "_bucket", (*tags, ("le", le)), cum)
            fmt(name + "_sum", tags, round(hsum, 9))
            fmt(name + "_count", tags, hcount)
        # device-dispatch path counters ("op" or "op[path]" keys)
        try:
            from m3_tpu.utils import dispatch

            items = sorted(dispatch.counters.items())
        except Exception:  # noqa: BLE001 - never break /metrics
            items = []
        for key, v in items:
            op, _, path = key.partition("[")
            tags = (("op", op),)
            if path:
                tags += (("path", path.rstrip("]")),)
            fmt("m3_dispatch_ops_total", tags, v, "counter")
        return ("\n".join(out) + "\n").encode()


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


class Logger:
    """Structured JSON-lines logger (the zap role)."""

    LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}

    def __init__(self, name: str = "", level: str = "info", stream=None):
        self.name = name
        self.level = self.LEVELS[level]
        self.stream = stream if stream is not None else sys.stderr
        self.fields: dict = {}

    def with_fields(self, **fields) -> "Logger":
        lg = Logger(self.name, stream=self.stream)
        lg.level = self.level
        lg.fields = {**self.fields, **fields}
        return lg

    def _log(self, level: str, msg: str, **fields) -> None:
        if self.LEVELS[level] < self.level:
            return
        rec = {
            "ts": time.time(),
            "level": level,
            "logger": self.name,
            "msg": msg,
            **self.fields,
            **fields,
        }
        print(json.dumps(rec, default=str), file=self.stream, flush=True)

    def debug(self, msg: str, **fields) -> None:
        self._log("debug", msg, **fields)

    def info(self, msg: str, **fields) -> None:
        self._log("info", msg, **fields)

    def warn(self, msg: str, **fields) -> None:
        self._log("warn", msg, **fields)

    def error(self, msg: str, **fields) -> None:
        self._log("error", msg, **fields)
