"""Device-dispatch policy for the hot serving paths.

Each serving-path op (aggregator flush reductions, postings bitmap algebra,
PromQL temporal math) has a numpy host implementation and a jax device
kernel. This module decides which runs:

- ``M3_TPU_DEVICE_OPS=1`` forces the device path (tests use this to assert
  kernel parity), ``=0`` forces host numpy;
- otherwise the device path runs when an accelerator backend is live and
  the workload is big enough to amortize dispatch (~O(100us) per call), the
  same batching rationale as the reference's insert-queue batching
  (/root/reference/src/dbnode/storage/shard_insert_queue.go).

Counters record which path executed so tests (and /metrics) can verify the
device path actually serves production queries — the round-1 failure mode
was device kernels that only tests invoked.
"""

from __future__ import annotations

import os
import weakref
from collections import Counter

counters: Counter = Counter()

# below this many elements the fixed dispatch cost dominates on any backend
DEFAULT_DEVICE_THRESHOLD = 16_384

_accel_cache: bool | None = None


def _accelerator_present() -> bool:
    """True when jax has an ALREADY-INITIALIZED accelerator backend.

    Never imports jax and never triggers backend initialization: both can
    hang indefinitely when the axon TPU tunnel is down, and a query thread
    must not be the one to pay (or wedge on) PJRT init. The device path
    therefore activates only after something else — the ingest/encode
    pipeline, service startup — has successfully initialized the backend."""
    global _accel_cache
    if _accel_cache is None:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return False  # leave cache unset: jax may be imported later
        try:
            from jax._src import xla_bridge

            backends = xla_bridge._backends  # populated only after init
            if not backends:
                return False  # leave cache unset: init may happen later
            _accel_cache = jax.default_backend() not in ("cpu",)
        except Exception:
            _accel_cache = False
    return _accel_cache


def jax_ready(force_env: str = "M3_TPU_QUERY_COMPILE") -> bool:
    """True when a serving path may touch jax WITHOUT risking a wedge:
    jax is already imported (the ingest/encode pipeline or service
    startup initialized it), or the operator explicitly forced the path
    (``force_env=1`` accepts the import). The shared rung under the
    whole-query compiler and the device-compiled index — mirrors
    _accelerator_present's dead-tunnel caution: a query thread must
    never be the first importer."""
    import sys

    if "jax" in sys.modules:
        return True
    return os.environ.get(force_env) == "1"


def use_device(n: int, threshold: int = DEFAULT_DEVICE_THRESHOLD) -> bool:
    force = os.environ.get("M3_TPU_DEVICE_OPS")
    if force == "1":
        return True
    if force == "0":
        return False
    return n >= threshold and _accelerator_present()


def record(op: str, device: bool) -> None:
    counters[f"{op}[{'device' if device else 'host'}]"] += 1


def next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length() if n > 2 else max(n, 1)


def next_bucket(n: int, multiple: int = 1) -> int:
    """Smallest of {2^k, 3*2^(k-1)} >= n: half-octave shape buckets.

    The whole-query compiler pads its matrix axes with these instead of
    plain powers of two — worst-case padding waste drops from 2x to
    1.33x (the padded cells are real work for a fused [S, T] program)
    while the compile count per axis stays O(log), just with twice the
    constant.

    ``multiple`` > 1 additionally requires the bucket to divide evenly
    (the sharded compute plane pads its series axis to a multiple of the
    mesh size so every device owns the same row count): prefer the next
    ladder rung that divides WHEN it costs no more than rounding the
    bucket up to the multiple (keeps 2/3-smooth mesh sizes on the
    ladder); otherwise round up — never more than one ``multiple`` of
    extra padding, and deterministic per (n, multiple) either way, so
    shape-bucket reuse is unaffected."""
    p = next_pow2(n)
    half = 3 * p // 4
    b = half if 0 < n <= half else p
    if multiple > 1 and b % multiple:
        r = b + (-b) % multiple
        c = max(b, 2)
        for _ in range(4):
            # next half-octave rung: 2^k -> 3*2^(k-1), 3*2^(k-1) -> 2^(k+1)
            c = 3 * c // 2 if (c & (c - 1)) == 0 else 4 * c // 3
            if c % multiple == 0 and c <= r:
                return c
        return r
    return b


# -- jit/plan-cache telemetry ------------------------------------------------
#
# Every XLA entry point on the serving paths is a jax.jit'd function keyed
# on static args (shape bucket, unit, impl). Whether a call HIT that plan
# cache or paid a trace+compile is the number the whole-query-compilation
# work (ROADMAP #2) will be judged against — so the dispatch layer records
# it: jit_tracker() wraps a call site, diffs the jitted function's cache
# size across the call, and lands hit/miss counters plus a compile-time
# histogram in the metrics registry (visible on /metrics, the self-scrape
# and the exporter).

_jit_scopes: dict = {}


def _jit_scope(op: str, result: str):
    key = (op, result)
    sc = _jit_scopes.get(key)
    if sc is None:
        from m3_tpu.utils.instrument import default_registry

        sc = default_registry().root_scope("compute").subscope(
            "jit", op=op, result=result)
        _jit_scopes[key] = sc
    return sc


# per-jitted-function last-seen executable-cache size: the eviction
# ground truth. An entry that disappears between tracked calls
# (jax.clear_caches(), a donated/evicted executable) shrinks the cache,
# which would make the next call's size diff under-report a re-trace as
# a hit — comparing against the LAST SEEN size catches both the
# eviction (compute_jit_evictions{op}) and the subsequent re-compile.
# Weak keys: a dropped program factory must not pin its executables.
_last_sizes: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class jit_tracker:
    """`with jit_tracker("m3tsz_decode", jitted_fn, sig="..."): ...` —
    records compute.jit_calls{op,result=hit|miss}; on a miss, the
    trace+compile wall time into compute.jit_compile_seconds{op}; on a
    hit (with a ``sig``), the execute wall into
    compute.execute_seconds{op,sig} and the per-program ledger
    (utils/compute_stats). The jitted function's private executable
    cache (`_cache_size`) is the ground truth; entries that vanished
    since the last tracked call bump compute_jit_evictions{op}. A jax
    build without `_cache_size` records every call as a hit with no
    compile histogram (counters stay meaningful, never wrong).

    ``lower`` (zero-arg callable returning a ``jax.stages.Lowered``,
    closing over the call's args) lets a miss capture the program's
    static cost profile once per compile."""

    def __init__(self, op: str, jitted_fn, sig: str | None = None,
                 lower=None):
        self.op = op
        self.sig = sig
        self._lower = lower
        self._fn = jitted_fn
        self._size_fn = getattr(jitted_fn, "_cache_size", None)
        # ground-truth compile outcome of the wrapped call, readable after
        # the with-block (the whole-query compiler keys its plan-cache
        # hit/miss accounting off this rather than guessing)
        self.miss = False
        # wrapped-call wall time, readable after the with-block (the
        # explain `device` block attributes it per query)
        self.seconds = 0.0

    def __enter__(self):
        import time

        self._before = self._size_fn() if self._size_fn is not None else None
        if self._before is not None:
            try:
                last = _last_sizes.get(self._fn)
            except TypeError:  # non-weakref-able callable
                last = None
            if last is not None and self._before < last:
                from m3_tpu.utils import compute_stats

                compute_stats.record_evictions(self.op, last - self._before)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        dt = self.seconds = time.perf_counter() - self._t0
        after = self._size_fn() if self._size_fn is not None else None
        miss = self.miss = self._before is not None and after > self._before
        if after is not None:
            try:
                _last_sizes[self._fn] = after
            except TypeError:
                pass
        result = "miss" if miss else "hit"
        counters[f"jit_{self.op}[{result}]"] += 1
        sc = _jit_scope(self.op, result)
        sc.counter("calls")
        if exc and exc[0] is not None:
            return False  # the call raised: no execute/compile attribution
        from m3_tpu.utils import compute_stats

        if miss:
            # the whole call IS the compile on a miss (execution time is
            # noise next to trace+lower+compile)
            sc.observe("compile_seconds", dt)
            compute_stats.record_compile(self.op, self.sig or "default", dt)
            if self._lower is not None:
                compute_stats.capture_profile(
                    self.op, self.sig or "default", self._lower)
        elif self._before is not None:
            compute_stats.record_execute(self.op, self.sig or "default", dt)
        return False
