"""Structured partial-result warnings for degraded cluster reads.

The reference coordinator attaches warning headers when a fanout returns
incomplete data (warn-on-partial-results mode) instead of failing the
whole query. This module is that contract for every read facade here:
when consistency/coverage is still met but some replica, host, or zone
failed, the read SUCCEEDS and carries one `ReadWarning` per degraded leg,
so callers (HTTP APIs, dashboards, tests) can distinguish "complete" from
"served degraded" without parsing log lines.

Producers: client/session.Session.fetch/fetch_many (scope "session",
name = host) and query/fanout.FanoutNamespace reads (scope "fanout",
name = zone). Consumers read them from the `warnings` out-param or the
facade's `last_warnings` attribute (reset per call).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReadWarning:
    scope: str   # which facade degraded: "session" | "fanout"
    name: str    # the failed leg: host id or zone name
    reason: str  # stringified cause, for operators

    def __str__(self) -> str:
        return f"{self.scope}:{self.name}: {self.reason}"
