"""Pure-Python snappy block format codec.

Prometheus remote read/write bodies are snappy-compressed protobuf; no
snappy library ships in this image, so: full decompressor for the block
format, and a valid literal-only compressor for responses (any conformant
snappy decoder accepts all-literal streams; we trade ratio for zero deps).
"""

from __future__ import annotations


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    out = shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def decompress(data: bytes) -> bytes:
    if not data:
        return b""
    n, pos = _read_uvarint(data, 0)
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length < 60:
                length += 1
            else:
                nbytes = length - 59
                length = int.from_bytes(data[pos : pos + nbytes], "little") + 1
                pos += nbytes
            out += data[pos : pos + length]
            pos += length
        else:
            if kind == 1:
                length = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: corrupt copy offset")
            # overlapping copies are allowed and common
            start = len(out) - offset
            for i in range(length):
                out.append(out[start + i])
    if len(out) != n:
        raise ValueError(f"snappy: length mismatch {len(out)} != {n}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """Literal-only snappy stream (valid, uncompressed payload)."""
    if not data:
        return b"\x00"
    out = bytearray()
    n = len(data)
    while n:
        out.append((n & 0x7F) | (0x80 if n > 0x7F else 0))
        n >>= 7
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        length = len(chunk)
        if length <= 60:
            out.append(((length - 1) << 2) | 0)
        else:
            out.append((61 << 2) | 0)  # 2-byte length literal
            out += (length - 1).to_bytes(2, "little")
        out += chunk
        pos += length
    return bytes(out)
