"""OTLP-style telemetry export: drain span rings + metric registries to a
collector endpoint.

The reference deployment sidecars an OpenTelemetry collector next to every
process; this module is the in-house equivalent for all four services
(coordinator, dbnode, aggregator, kvd): a background drainer periodically
snapshots the process tracer's NEW spans (`Tracer.export_since` cursor —
each span ships at most once) and the metrics registry, wraps them in an
OTLP-shaped envelope (`resource` / `scopeSpans` / `scopeMetrics`), and
ships them to a pluggable sink:

- ``HTTPSink`` POSTs JSON to a collector endpoint (`M3_TPU_EXPORT_ENDPOINT`
  or the service config's ``export.endpoint``);
- ``FileSink`` appends JSON lines (`M3_TPU_EXPORT_FILE` / ``export.file``)
  — the test backend and a poor-man's collector for `em` dtests.

Backpressure contract: the hot path NEVER blocks on export. Recording
stays exactly as cheap as without an exporter (the drainer pulls on its
own thread); payloads queue in a BOUNDED deque and a sink outage drops the
oldest payload per overflow, counted on ``exporter_dropped_payloads`` /
``exporter_dropped_spans`` — so a dead collector costs bounded memory and
visible counters, nothing else. With no endpoint/file configured,
``exporter_from_config`` returns None and the services skip the thread
entirely.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from m3_tpu.utils.instrument import MetricsRegistry, default_registry


class FileSink:
    """JSON-lines file backend (tests, dtests)."""

    def __init__(self, path: str):
        self.path = path

    def ship(self, payload: dict) -> None:
        line = json.dumps(payload, default=str)
        with open(self.path, "a") as f:
            f.write(line + "\n")


class HTTPSink:
    """POST each payload as JSON to a collector endpoint."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        self.endpoint = endpoint
        self.timeout_s = timeout_s

    def ship(self, payload: dict) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.endpoint, data=json.dumps(payload, default=str).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            r.read()


class TelemetryExporter:
    """Bounded-queue drainer: collect -> enqueue -> ship, on a daemon
    thread (or driven manually via `tick()` in tests/service loops)."""

    def __init__(self, service: str, sink, interval_s: float = 10.0,
                 queue_max: int = 64, registry: MetricsRegistry | None = None,
                 tracer=None):
        from m3_tpu.utils import trace

        self.service = service
        self.sink = sink
        self.interval_s = interval_s
        self.registry = registry or default_registry()
        self.tracer = tracer or trace.default_tracer()
        self._queue: deque[dict] = deque()
        self.queue_max = queue_max
        self._cursor = 0
        self._profile_cursor = 0  # sampling-profiler snapshot cursor
        self.dropped_payloads = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # the exporter's own health rides the same registry it exports
        self._scope = self.registry.root_scope("exporter") \
            .subscope("svc", service=service)
        # saturation plane: the bounded payload queue's depth/drops are
        # gauges refreshed at every registry snapshot
        from m3_tpu.utils.instrument import monitor_queue

        self._unmonitor = monitor_queue(
            "exporter", lambda: len(self._queue), lambda: self.queue_max,
            drops_fn=lambda: self.dropped_payloads, owner=self,
            service=service)

    # -- collection --

    def collect_once(self, now_ns: int | None = None) -> dict | None:
        """One export payload: spans recorded since the last collect plus
        a full metrics snapshot. None when there is nothing new to say
        (no new spans AND no metrics — a fresh idle process)."""
        from m3_tpu.utils import profiler

        now_ns = now_ns if now_ns is not None else time.time_ns()
        spans, self._cursor = self.tracer.export_since(self._cursor)
        # sampling-profiler snapshots ride the same cursor discipline as
        # spans: a sampling epoch ships at most once, an idle profiler
        # ships nothing
        prof, self._profile_cursor = profiler.default_profiler() \
            .export_since(self._profile_cursor)
        counters, gauges, timers, hists = self.registry.snapshot()
        if not spans and not counters and not gauges and not timers \
                and not hists and prof is None:
            return None
        metrics = []
        for (name, tags), v in counters.items():
            metrics.append({"name": name, "type": "counter",
                            "attributes": dict(tags), "value": v})
        for (name, tags), v in gauges.items():
            metrics.append({"name": name, "type": "gauge",
                            "attributes": dict(tags), "value": v})
        for (name, tags), (count, total_s, max_s) in timers.items():
            metrics.append({"name": name, "type": "timer",
                            "attributes": dict(tags), "count": count,
                            "sum": total_s, "max": max_s})
        for (name, tags), (bounds, counts, hsum, hcount) in hists.items():
            metrics.append({"name": name, "type": "histogram",
                            "attributes": dict(tags),
                            "bounds": list(bounds), "counts": list(counts),
                            "sum": hsum, "count": hcount})
        payload = {
            "resource": {"service.name": self.service,
                         "process.pid": os.getpid()},
            "time_unix_ns": now_ns,
            "scopeSpans": spans,
            "scopeMetrics": metrics,
        }
        if prof is not None:
            payload["scopeProfile"] = prof
        # device-compute attribution rides the same payload (the
        # histogram/gauge families above carry the rates; this block
        # carries the per-program ranking a dashboard can't rebuild
        # from bucketed data): top programs by device time + the
        # padding-waste ledger
        try:
            from m3_tpu.utils import compute_stats

            comp = compute_stats.debug_payload(top_n=10)
            if comp["programs"] or comp["waste"]:
                payload["scopeCompute"] = {
                    "programs": comp["programs"],
                    "waste": comp["waste"],
                    "jit_evictions": comp["jit_evictions"],
                }
        except Exception:  # noqa: BLE001 - telemetry must never break
            pass           # the export loop
        return payload

    # -- queue + ship --

    def _enqueue(self, payload: dict) -> None:
        with self._lock:
            while len(self._queue) >= self.queue_max:
                dropped = self._queue.popleft()
                self.dropped_payloads += 1
                self._scope.counter("dropped_payloads")
                self._scope.counter("dropped_spans",
                                    len(dropped.get("scopeSpans", ())))
            self._queue.append(payload)
            self._scope.gauge("queue_depth", len(self._queue))

    def _drain(self) -> int:
        """Ship queued payloads oldest-first; stop at the first sink
        failure (the rest retry next tick, bounded by the queue)."""
        shipped = 0
        while True:
            with self._lock:
                if not self._queue:
                    break
                payload = self._queue[0]
            try:
                self.sink.ship(payload)
            except Exception:  # noqa: BLE001 - sink outage: keep queued
                self._scope.counter("ship_errors")
                break
            with self._lock:
                # ships run on one drainer thread; the head is still ours
                if self._queue and self._queue[0] is payload:
                    self._queue.popleft()
            shipped += 1
            self._scope.counter("shipped_payloads")
            self._scope.counter("shipped_spans",
                                len(payload.get("scopeSpans", ())))
        with self._lock:
            self._scope.gauge("queue_depth", len(self._queue))
        return shipped

    def tick(self, now_ns: int | None = None) -> int:
        """One collect+enqueue+drain pass; returns payloads shipped."""
        payload = self.collect_once(now_ns)
        if payload is not None:
            self._enqueue(payload)
        return self._drain()

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- lifecycle --

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            from m3_tpu.utils import profiler

            hb = profiler.register_heartbeat(f"exporter.{self.service}",
                                             self.interval_s)
            try:
                while not self._stop.wait(self.interval_s):
                    hb.beat()
                    try:
                        self.tick()
                    except Exception:  # noqa: BLE001 - the drainer must
                        pass           # outlive transient sink weirdness
            finally:
                hb.close()

        self._thread = threading.Thread(
            target=loop, name=f"telemetry-export-{self.service}", daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop the drainer and attempt one final collect+ship."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)
            self._thread = None
        try:
            self.tick()
        except Exception:  # noqa: BLE001 - best-effort final flush
            pass
        self._unmonitor()


def exporter_from_config(config: dict | None, service: str,
                         registry: MetricsRegistry | None = None
                         ) -> TelemetryExporter | None:
    """Build the service's exporter from its config's ``export:`` section
    (file / endpoint / interval_s / queue_max), with
    ``M3_TPU_EXPORT_FILE`` / ``M3_TPU_EXPORT_ENDPOINT`` env overrides so
    processes without config files (kvd, dtest children) still export.
    Returns None when neither a file nor an endpoint is configured — the
    caller skips the drainer thread entirely."""
    cfg = dict((config or {}).get("export", {}) or {})
    file_path = os.environ.get("M3_TPU_EXPORT_FILE") or cfg.get("file")
    endpoint = os.environ.get("M3_TPU_EXPORT_ENDPOINT") or cfg.get("endpoint")
    if file_path:
        sink = FileSink(str(file_path))
    elif endpoint:
        sink = HTTPSink(str(endpoint))
    else:
        return None
    return TelemetryExporter(
        service, sink,
        interval_s=float(cfg.get("interval_s", 10.0)),
        queue_max=int(cfg.get("queue_max", 64)),
        registry=registry,
    )
