"""Device-compute observability plane: per-program execute telemetry,
static XLA program profiles, and padding-waste accounting.

`dispatch.jit_tracker` answers ONE question per tracked call — did it
hit the executable cache — and times the compile on a miss. Everything
downstream of that (which program burns the device time, what a shape
bucket costs in padded cells, whether a mesh actually changed the FLOP
bill) was invisible. This module is the accounting ledger behind the
tracker:

- ``record_execute``/``record_compile`` land per-(op, sig) wall time in
  a process table plus ``compute.execute`` histograms, with the same
  <=64-distinct-keys + ``other`` label-cap discipline the whole-query
  compiler applies to plan-shape labels (sig cardinality is bounded by
  the half-octave bucket ladder, but the metrics registry must survive
  an adversarial shape storm anyway).
- ``capture_profile`` stores the lowered program's ``cost_analysis()``
  (FLOPs, bytes accessed) once per compile. Backends that expose
  nothing degrade to a counted reason, never an exception — the
  analysis runs ONLY from a tracked miss, where the backend is live by
  construction, so it can never be the thing that pays PJRT init.
  ``memory_analysis`` needs a second AOT compile (jax's ``.compile()``
  does not share the jit executable cache), so it is opt-in via
  ``M3_TPU_COMPUTE_PROFILE_MEMORY=1``.
- ``record_waste`` accumulates logical-vs-padded element counts at the
  half-octave/slab padding seams (query slabs, postings tensors, ragged
  encode, windowed agg); a snapshot hook publishes them as
  ``compute.waste{site,axis}`` gauges so the ratio is fresh on every
  scrape with no refresh loop.
- ``register_device_cache`` lets device-resident caches (the hot tier,
  the per-segment postings columns) report entries+bytes without this
  module importing storage or index code: providers register when THEY
  import, the ledger only reads.
- ``debug_payload``/``handle_debug_compute`` render the whole plane as
  the ``/debug/compute`` JSON body shared by all four services. The
  payload path never imports jax and never triggers backend init (same
  no-init rule as ``dispatch._accelerator_present``): device memory is
  read only from an ALREADY-initialized backend, the plan cache only
  from an already-imported compiler module.

``M3_TPU_COMPUTE_STATS=0`` disarms the per-call paths (``arm()`` is the
programmatic toggle bench #16 flips); the table survives disarming so
``/debug/compute`` keeps its history.
"""

from __future__ import annotations

import json
import os
import threading

# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------

_armed = os.environ.get("M3_TPU_COMPUTE_STATS", "1") != "0"


def arm(on: bool) -> None:
    """Toggle the per-call recording paths (bench #16 overhead guard
    flips this); the accumulated table is kept either way."""
    global _armed
    _armed = bool(on)


def armed() -> bool:
    return _armed


# ---------------------------------------------------------------------------
# per-program table + sig label cap
# ---------------------------------------------------------------------------

_lock = threading.Lock()

# (op, sig) -> mutable stat row; bounded — overflow folds to (op, "other")
_TABLE_CAP = 512
_programs: dict = {}

# metrics-label discipline: first N distinct sigs get their own label,
# the tail folds to "other" (mirrors compiler._shape_label, PR 10)
_SIG_LABEL_CAP = 64
_sig_labels_seen: set = set()

_scopes: dict = {}


def _scope(kind: str, **tags):
    key = (kind, tuple(sorted(tags.items())))
    sc = _scopes.get(key)
    if sc is None:
        from m3_tpu.utils.instrument import default_registry

        sc = default_registry().root_scope("compute").subscope(kind, **tags)
        _scopes[key] = sc
    return sc


def _sig_label(sig: str) -> str:
    if sig in _sig_labels_seen:
        return sig
    with _lock:
        if sig in _sig_labels_seen:
            return sig
        if len(_sig_labels_seen) >= _SIG_LABEL_CAP:
            return "other"
        _sig_labels_seen.add(sig)
    return sig


def _row(op: str, sig: str) -> dict:
    key = (op, sig)
    row = _programs.get(key)
    if row is None:
        if len(_programs) >= _TABLE_CAP:
            key = (op, "other")
            row = _programs.get(key)
            if row is not None:
                return row
        row = _programs[key] = {
            "op": op, "sig": key[1], "calls": 0,
            "execute_calls": 0, "execute_seconds_total": 0.0,
            "execute_seconds_last": 0.0,
            "compiles": 0, "compile_seconds_total": 0.0,
        }
    return row


def record_execute(op: str, sig: str, seconds: float) -> None:
    """One tracked cache-HIT call: the wrapped wall time is device
    dispatch + execution (trace/compile excluded by definition)."""
    if not _armed:
        return
    with _lock:
        row = _row(op, sig)
        row["calls"] += 1
        row["execute_calls"] += 1
        row["execute_seconds_total"] += seconds
        row["execute_seconds_last"] = seconds
    # leaf "seconds" under the compute.execute scope: the exposition
    # family is compute_execute_seconds{op,sig}
    _scope("execute", op=op, sig=_sig_label(sig)).observe("seconds", seconds)


def record_compile(op: str, sig: str, seconds: float) -> None:
    """One tracked cache-MISS call (trace+lower+compile dominates the
    wall; the jit scope's compile_seconds histogram is recorded by the
    tracker itself — this lands the table attribution)."""
    if not _armed:
        return
    with _lock:
        row = _row(op, sig)
        row["calls"] += 1
        row["compiles"] += 1
        row["compile_seconds_total"] += seconds


def record_evictions(op: str, n: int) -> None:
    """Executable-cache entries that disappeared between tracked calls
    (clear_caches, donated/evicted executables) — the ground-truth
    eviction count behind compute_jit_evictions{op}."""
    if n <= 0:
        return
    _scope("jit_cache", op=op).counter("evictions", float(n))
    with _lock:
        _evictions[op] = _evictions.get(op, 0) + n


_evictions: dict = {}


# ---------------------------------------------------------------------------
# static program profiles (cost/memory analysis, captured once per compile)
# ---------------------------------------------------------------------------

# degrade reasons are a closed set so the counter label stays bounded
_DEGRADE_REASONS = ("lower_failed", "cost_unavailable", "cost_failed",
                    "memory_unavailable", "profile_failed")
_degrades: dict = {}


def _degrade(reason: str) -> None:
    if reason not in _DEGRADE_REASONS:
        reason = "profile_failed"
    _scope("profile", reason=reason).counter("degraded")
    with _lock:
        _degrades[reason] = _degrades.get(reason, 0) + 1


def capture_profile(op: str, sig: str, lower) -> None:
    """Attach the lowered program's static cost profile to (op, sig).

    ``lower`` is a zero-arg callable returning a ``jax.stages.Lowered``
    (the call site closes over the program + its args). Called ONLY
    from a tracked miss, so jax is imported and the backend is live by
    construction; every step still degrades to a counted reason rather
    than raising — telemetry must never fail a query.
    """
    if not _armed:
        return
    profile: dict = {}
    try:
        try:
            lowered = lower()
        except Exception:  # noqa: BLE001 - counted, never fatal
            _degrade("lower_failed")
            return
        cost_failed = False
        try:
            cost = lowered.cost_analysis()
        except Exception:  # noqa: BLE001
            _degrade("cost_failed")
            cost, cost_failed = None, True
        if isinstance(cost, (list, tuple)):  # older jax: one dict per device
            cost = cost[0] if cost else None
        if isinstance(cost, dict) and ("flops" in cost
                                       or "bytes accessed" in cost):
            if "flops" in cost:
                profile["flops"] = float(cost["flops"])
            if "bytes accessed" in cost:
                profile["bytes_accessed"] = float(cost["bytes accessed"])
        elif not cost_failed:
            _degrade("cost_unavailable")
        if os.environ.get("M3_TPU_COMPUTE_PROFILE_MEMORY") == "1":
            # pays a SECOND XLA compile (AOT .compile() does not share
            # the jit executable cache) — operator opt-in only
            try:
                mem = lowered.compile().memory_analysis()
                profile["temp_bytes"] = float(mem.temp_size_in_bytes)
                profile["output_bytes"] = float(mem.output_size_in_bytes)
                profile["argument_bytes"] = float(mem.argument_size_in_bytes)
            except Exception:  # noqa: BLE001
                _degrade("memory_unavailable")
    except Exception:  # noqa: BLE001 - belt over braces: never fatal
        _degrade("profile_failed")
        return
    if profile:
        with _lock:
            _row(op, sig).setdefault("profile", {}).update(profile)


def profile_for(op: str, sig: str) -> dict | None:
    """The stored static profile for (op, sig), if one was captured."""
    with _lock:
        row = _programs.get((op, sig))
        return dict(row["profile"]) if row and "profile" in row else None


# ---------------------------------------------------------------------------
# padding-waste accounting at the half-octave / slab seams
# ---------------------------------------------------------------------------

# (site, axis) -> [logical_total, padded_total, logical_last, padded_last]
_waste: dict = {}


def record_waste(site: str, axis: str, logical: int, padded: int) -> None:
    """One padded tensor axis: ``logical`` real elements shipped in a
    ``padded``-element bucket. Sites/axes are code literals (bounded
    label set); totals feed the compute.waste{site,axis} gauges."""
    if not _armed or padded <= 0:
        return
    with _lock:
        acc = _waste.get((site, axis))
        if acc is None:
            acc = _waste[(site, axis)] = [0, 0, 0, 0]
        acc[0] += int(logical)
        acc[1] += int(padded)
        acc[2] = int(logical)
        acc[3] = int(padded)


def waste_ratio(site: str, axis: str) -> float | None:
    """Cumulative fraction of padded cells that carry no real data."""
    with _lock:
        acc = _waste.get((site, axis))
    if not acc or not acc[1]:
        return None
    return 1.0 - acc[0] / acc[1]


def _publish_waste(registry) -> None:
    # snapshot hook: gauges are fresh at every scrape, no refresh loop
    with _lock:
        items = {k: list(v) for k, v in _waste.items()}
    for (site, axis), (ltot, ptot, _ll, _pl) in items.items():
        if not ptot:
            continue
        sc = registry.root_scope("compute").subscope(
            "waste", site=site, axis=axis)
        sc.gauge("logical_elements", float(ltot))
        sc.gauge("padded_elements", float(ptot))
        sc.gauge("waste_ratio", 1.0 - ltot / ptot)


# ---------------------------------------------------------------------------
# device-resident cache providers (hot tier, postings columns, ...)
# ---------------------------------------------------------------------------

# name -> zero-arg callable returning a {"entries": int, "bytes": int,
# ...} dict; providers register when their module imports, so the
# ledger never has to import storage/index code (and a dbnode that
# never compiled a query reports nothing rather than importing the
# whole query plane to say so)
_device_caches: dict = {}


def register_device_cache(name: str, fn) -> None:
    _device_caches[name] = fn


def _device_cache_stats() -> dict:
    out = {}
    for name, fn in list(_device_caches.items()):
        try:
            out[name] = fn()
        except Exception:  # noqa: BLE001 - a provider bug must not
            pass           # break the debug surface
    return out


def _publish_device_caches(registry) -> None:
    for name, stats in _device_cache_stats().items():
        sc = registry.root_scope("compute").subscope(
            "device_cache", cache=name)
        for field, val in stats.items():
            if isinstance(val, (int, float)):
                sc.gauge(field, float(val))


def _snapshot_hook(registry) -> None:
    _publish_waste(registry)
    _publish_device_caches(registry)


def _register_hook() -> None:
    from m3_tpu.utils.instrument import register_snapshot_hook

    register_snapshot_hook(_snapshot_hook)


_register_hook()


# ---------------------------------------------------------------------------
# /debug/compute payload (shared by all four services)
# ---------------------------------------------------------------------------

def device_memory() -> list[dict]:
    """Per-device memory from an ALREADY-initialized jax backend; never
    imports jax, never triggers PJRT init (dispatch no-init doctrine —
    a debug scrape must not be the thing that wedges on a dead
    tunnel). CPU devices report no memory_stats and are skipped."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return []
    out = []
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:  # not initialized: do not trigger
            return []
        for d in jax.devices():
            stats = d.memory_stats() if hasattr(d, "memory_stats") else None
            if not stats:
                continue
            out.append({"device": int(d.id), "platform": str(d.platform),
                        "bytes_in_use": int(stats.get("bytes_in_use", 0))})
    except Exception:  # noqa: BLE001 - a backend quirk must not break
        return out      # the debug surface
    return out


def _plan_cache_stats() -> dict | None:
    # only from an already-imported compiler: the debug surface must not
    # be the importer of the whole query plane
    import sys

    compiler = sys.modules.get("m3_tpu.query.compiler")
    if compiler is None:
        return None
    try:
        return compiler.plan_cache_stats()
    except Exception:  # noqa: BLE001
        return None


def debug_payload(top_n: int = 20) -> dict:
    """The /debug/compute JSON body: top-N programs by device time,
    plan-cache occupancy, jit evictions, padding waste, device-resident
    cache bytes, per-device memory, profile degrades."""
    with _lock:
        rows = [dict(r) for r in _programs.values()]
        evict = dict(_evictions)
        degr = dict(_degrades)
        waste = {f"{site}/{axis}": {
            "logical": acc[0], "padded": acc[1],
            "waste_ratio": round(1.0 - acc[0] / acc[1], 6) if acc[1] else 0.0,
        } for (site, axis), acc in _waste.items()}
    rows.sort(key=lambda r: r["execute_seconds_total"], reverse=True)
    return {
        "armed": _armed,
        "programs": rows[:max(top_n, 0)],
        "plan_cache": _plan_cache_stats(),
        "jit_evictions": evict,
        "waste": waste,
        "device_caches": _device_cache_stats(),
        "device_memory": device_memory(),
        "profile_degrades": degr,
    }


def handle_debug_compute(method: str, q: dict, body: bytes):
    """Shared route handler -> (status, payload, content_type) for
    GET /debug/compute[?top=N] on all four services (same signature
    contract as profiler.handle_debug_profile)."""
    if method != "GET":
        return (405, json.dumps({"error": "GET only"}).encode(),
                "application/json")
    try:
        top_n = int(q.get("top", ["20"])[0]) if q else 20
    except (TypeError, ValueError):
        top_n = 20
    return (200, json.dumps(debug_payload(top_n)).encode(),
            "application/json")


def reset() -> None:
    """Test hook: drop every accumulator (table, waste, evictions,
    degrades, sig labels) — NOT the registered cache providers."""
    global _armed
    with _lock:
        _programs.clear()
        _waste.clear()
        _evictions.clear()
        _degrades.clear()
        _sig_labels_seen.clear()
    _armed = os.environ.get("M3_TPU_COMPUTE_STATS", "1") != "0"
