"""Series identity: IDs and tag sets, plus the tag wire codec.

Equivalent roles to the reference's src/x/ident (IDs/tags) and
src/x/serialize (tag wire format, serialize/types.go:37-108): a compact
length-prefixed binary encoding used in fileset index entries and on the
wire. Layout: u16 count, then per tag (u16 len + name, u16 len + value).
"""

from __future__ import annotations

import struct
from typing import Iterable

HEADER_MAGIC = 0x4D33  # "M3"


def encode_tags(tags: Iterable[tuple[bytes, bytes]]) -> bytes:
    tags = list(tags)
    out = bytearray(struct.pack(">HH", HEADER_MAGIC, len(tags)))
    for name, value in tags:
        out += struct.pack(">H", len(name)) + name
        out += struct.pack(">H", len(value)) + value
    return bytes(out)


def decode_tags(data: bytes) -> list[tuple[bytes, bytes]]:
    magic, count = struct.unpack_from(">HH", data, 0)
    if magic != HEADER_MAGIC:
        raise ValueError(f"bad tag header magic {magic:#x}")
    off = 4
    tags = []
    for _ in range(count):
        (nlen,) = struct.unpack_from(">H", data, off)
        off += 2
        name = data[off : off + nlen]
        off += nlen
        (vlen,) = struct.unpack_from(">H", data, off)
        off += 2
        value = data[off : off + vlen]
        off += vlen
        tags.append((name, value))
    return tags


def _escape(b: bytes) -> bytes:
    """Escape the ID separators so distinct tag sets can't collide."""
    return b.replace(b"\\", b"\\\\").replace(b"|", b"\\|").replace(b"=", b"\\=")


def tags_to_id(metric_name: bytes, tags: Iterable[tuple[bytes, bytes]]) -> bytes:
    """Canonical series ID from metric name + sorted tags (the role of
    metric/id/m3 tag-aware IDs in the reference). Separators inside names/
    values are escaped, making the encoding injective."""
    parts = [_escape(metric_name)]
    for name, value in sorted(tags):
        parts.append(_escape(name) + b"=" + _escape(value))
    return b"|".join(parts)
