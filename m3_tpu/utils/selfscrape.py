"""Self-monitoring loop: ingest the process's own metrics registry into a
`_m3_system` namespace — M3 monitors M3.

The reference deployment scrapes each component's /metrics with a separate
Prometheus and often remote-writes that back into M3. This module closes
the loop in-process: a scrape snapshots utils/instrument's registry (one
lock acquisition) and writes every sample through the normal ingest path
into a dedicated namespace, so platform health — including p99s over the
latency histograms, via histogram_quantile over the `_bucket` series — is
queryable with the platform's own PromQL (`?namespace=_m3_system` on the
query endpoints).

Series naming mirrors the Prometheus exposition exactly (name mangling,
`_bucket`/`_sum`/`_count` suffixes, `le` labels), so dashboards written
against /metrics port to PromQL over `_m3_system` unchanged.
"""

from __future__ import annotations

import math
import time

from m3_tpu.utils.instrument import (
    MetricsRegistry,
    _fmt_number,
    _prom_name,
    default_registry,
)

SELF_NAMESPACE = "_m3_system"


def ensure_namespace(db, namespace: str = SELF_NAMESPACE) -> bool:
    """Create the self-monitoring namespace on the LOCAL storage under
    `db` (facades unwrap to their local zone). False when there is no
    local storage to host it — a pure cluster-client coordinator
    (ClusterDatabase) routes writes to nodes that never registered the
    namespace, so self-scrape stays off there."""
    target = getattr(db, "local", db)
    create = getattr(target, "create_namespace", None)
    # a real local Database owns a block cache; client facades don't
    if create is None or getattr(target, "block_cache", None) is None:
        return False
    create(namespace)
    return True


def _entry(out: list, name: str, tags, t_ns: int, value: float,
           extra_tags: tuple = ()) -> None:
    if math.isnan(value) or math.isinf(value):
        return  # not representable as a sane sample; /metrics still has it
    fields = sorted(
        [(str(k).encode(), str(v).encode()) for k, v in tags]
        + [(str(k).encode(), str(v).encode()) for k, v in extra_tags]
    )
    out.append((_prom_name(name).encode(), fields, t_ns, float(value)))


def scrape_once(db, registry: MetricsRegistry | None = None,
                namespace: str = SELF_NAMESPACE,
                now_ns: int | None = None) -> int:
    """One self-scrape: registry snapshot -> ONE batched ingest. Every
    sample of the tick ships through db.write_batch as a single
    columnar storage pass (per-sample write_tagged only for facades
    without the batch surface). Returns the number of samples written.
    The caller created the namespace (ensure_namespace) — a missing one
    raises like any bad write."""
    registry = registry or default_registry()
    now_ns = now_ns if now_ns is not None else time.time_ns()
    counters, gauges, timers, hists = registry.snapshot()
    entries: list = []
    for (name, tags), v in counters.items():
        _entry(entries, name, tags, now_ns, v)
    for (name, tags), v in gauges.items():
        _entry(entries, name, tags, now_ns, v)
    for (name, tags), (count, total_s, max_s) in timers.items():
        _entry(entries, name + "_count", tags, now_ns, count)
        _entry(entries, name + "_total_seconds", tags, now_ns, total_s)
        _entry(entries, name + "_max_seconds", tags, now_ns, max_s)
    for (name, tags), (bounds, counts, hsum, hcount) in hists.items():
        running = 0
        for ub, c in zip(bounds, counts):
            running += c
            _entry(entries, name + "_bucket", tags, now_ns, running,
                   extra_tags=(("le", _fmt_number(ub)),))
        _entry(entries, name + "_bucket", tags, now_ns,
               running + counts[-1], extra_tags=(("le", "+Inf"),))
        _entry(entries, name + "_sum", tags, now_ns, hsum)
        _entry(entries, name + "_count", tags, now_ns, hcount)
    # device-dispatch path counters, same shape /metrics exposes them in
    # (m3_dispatch_ops_total{op,path}) so dashboards port unchanged
    try:
        from m3_tpu.utils import dispatch

        items = sorted(dispatch.counters.items())
    except Exception:  # noqa: BLE001 - never break the scrape
        items = []
    for key, v in items:
        op, _, path = key.partition("[")
        tags = (("op", op),) + ((("path", path.rstrip("]")),) if path else ())
        _entry(entries, "m3_dispatch_ops_total", tags, now_ns, v)
    write_batch = getattr(db, "write_batch", None)
    if write_batch is not None:
        results = write_batch(namespace, entries)
        bad = [r for r in results if r is not None]
        if bad:  # scrape failures must stay loud, like the old raise
            raise RuntimeError(
                f"self-scrape: {len(bad)}/{len(entries)} samples failed "
                f"(first: {bad[0]})")
        return len(entries)
    for name, fields, t_ns, v in entries:
        db.write_tagged(namespace, name, fields, t_ns, v)
    return len(entries)


class SelfMonitor:
    """Tick-driven self-scrape for a service loop: call `maybe_scrape()`
    every tick; it scrapes when `interval_s` has elapsed."""

    def __init__(self, db, interval_s: float = 10.0,
                 namespace: str = SELF_NAMESPACE, registry=None,
                 clock=time.monotonic):
        self.db = db
        self.interval_s = interval_s
        self.namespace = namespace
        self.registry = registry or default_registry()
        self._clock = clock
        self._last = 0.0
        self.samples_written = 0
        self.enabled = ensure_namespace(db, namespace)

    def maybe_scrape(self, now_ns: int | None = None) -> int:
        if not self.enabled:
            return 0
        now = self._clock()
        if now - self._last < self.interval_s:
            return 0
        self._last = now
        n = scrape_once(self.db, self.registry, self.namespace, now_ns)
        self.samples_written += n
        return n
