"""Self-monitoring loop: ingest the process's own metrics registry into a
`_m3_system` namespace — M3 monitors M3.

The reference deployment scrapes each component's /metrics with a separate
Prometheus and often remote-writes that back into M3. This module closes
the loop in-process: a scrape snapshots utils/instrument's registry (one
lock acquisition) and writes every sample through the normal ingest path
into a dedicated namespace, so platform health — including p99s over the
latency histograms, via histogram_quantile over the `_bucket` series — is
queryable with the platform's own PromQL (`?namespace=_m3_system` on the
query endpoints).

Series naming mirrors the Prometheus exposition exactly (name mangling,
`_bucket`/`_sum`/`_count` suffixes, `le` labels), so dashboards written
against /metrics port to PromQL over `_m3_system` unchanged.
"""

from __future__ import annotations

import math
import time

from m3_tpu.utils.instrument import (
    MetricsRegistry,
    _fmt_number,
    _prom_name,
    default_registry,
)

SELF_NAMESPACE = "_m3_system"

_PAGE_SIZE = 4096
try:
    import os as _os

    _PAGE_SIZE = _os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):
    pass


def rss_bytes() -> int:
    """Process resident-set size in bytes (0 when unreadable): the one
    RSS reader both observability surfaces share — the `_m3_system`
    process_rss_bytes gauge here and /debug/profile + the rig
    trajectory (utils/profiler) must never disagree about RSS."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        try:
            import resource
            import sys as _sys

            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is KILOBYTES on linux but BYTES on darwin — and
            # darwin is exactly where the /proc path above fails. (Peak
            # rss, not current: the best this fallback can do.)
            return peak if _sys.platform == "darwin" else peak * 1024
        except Exception:  # noqa: BLE001 - no rss source on this platform
            return 0


def record_process_gauges(registry: MetricsRegistry | None = None) -> None:
    """Compute-plane health gauges, refreshed each self-scrape tick:
    process RSS (from /proc/self/statm, getrusage fallback) and per-device
    accelerator memory in use (jax memory_stats — only when a backend is
    ALREADY initialized, same no-init rule as utils/dispatch: a scrape
    must never be the thing that pays, or wedges on, PJRT init). CPU
    backends report no memory_stats and are skipped."""
    registry = registry or default_registry()
    scope = registry.root_scope("process")
    rss = rss_bytes()
    if rss:
        scope.gauge("rss_bytes", float(rss))
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:  # not initialized: do not trigger it
            return
        dev_scope = registry.root_scope("device")
        for d in jax.devices():
            stats = d.memory_stats() if hasattr(d, "memory_stats") else None
            if not stats:
                continue  # CPU devices report none
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                dev_scope.subscope("mem", device=str(d.id),
                                   platform=d.platform) \
                    .gauge("bytes_in_use", float(in_use))
    except Exception:  # noqa: BLE001 - never break the scrape over a
        pass           # backend quirk


def ensure_namespace(db, namespace: str = SELF_NAMESPACE) -> bool:
    """Create the self-monitoring namespace on the LOCAL storage under
    `db` (facades unwrap to their local zone). False when there is no
    local storage to host it — a pure cluster-client coordinator
    (ClusterDatabase) routes writes to nodes that never registered the
    namespace, so self-scrape stays off there."""
    target = getattr(db, "local", db)
    create = getattr(target, "create_namespace", None)
    # a real local Database owns a block cache; client facades don't
    if create is None or getattr(target, "block_cache", None) is None:
        return False
    create(namespace)
    return True


def _entry(out: list, name: str, tags, t_ns: int, value: float,
           extra_tags: tuple = ()) -> None:
    if math.isnan(value) or math.isinf(value):
        return  # not representable as a sane sample; /metrics still has it
    fields = sorted(
        [(str(k).encode(), str(v).encode()) for k, v in tags]
        + [(str(k).encode(), str(v).encode()) for k, v in extra_tags]
    )
    out.append((_prom_name(name).encode(), fields, t_ns, float(value)))


def scrape_once(db, registry: MetricsRegistry | None = None,
                namespace: str = SELF_NAMESPACE,
                now_ns: int | None = None) -> int:
    """One self-scrape: registry snapshot -> ONE batched ingest. Every
    sample of the tick ships through db.write_batch as a single
    columnar storage pass (per-sample write_tagged only for facades
    without the batch surface). Returns the number of samples written.
    The caller created the namespace (ensure_namespace) — a missing one
    raises like any bad write."""
    registry = registry or default_registry()
    now_ns = now_ns if now_ns is not None else time.time_ns()
    # refresh compute-plane gauges (RSS, device memory) so the tick's
    # snapshot carries them alongside the seam histograms
    record_process_gauges(registry)
    counters, gauges, timers, hists = registry.snapshot()
    entries: list = []
    for (name, tags), v in counters.items():
        _entry(entries, name, tags, now_ns, v)
    for (name, tags), v in gauges.items():
        _entry(entries, name, tags, now_ns, v)
    for (name, tags), (count, total_s, max_s) in timers.items():
        _entry(entries, name + "_count", tags, now_ns, count)
        _entry(entries, name + "_total_seconds", tags, now_ns, total_s)
        _entry(entries, name + "_max_seconds", tags, now_ns, max_s)
    for (name, tags), (bounds, counts, hsum, hcount) in hists.items():
        running = 0
        for ub, c in zip(bounds, counts):
            running += c
            _entry(entries, name + "_bucket", tags, now_ns, running,
                   extra_tags=(("le", _fmt_number(ub)),))
        _entry(entries, name + "_bucket", tags, now_ns,
               running + counts[-1], extra_tags=(("le", "+Inf"),))
        _entry(entries, name + "_sum", tags, now_ns, hsum)
        _entry(entries, name + "_count", tags, now_ns, hcount)
    # device-dispatch path counters, same shape /metrics exposes them in
    # (m3_dispatch_ops_total{op,path}) so dashboards port unchanged
    try:
        from m3_tpu.utils import dispatch

        items = sorted(dispatch.counters.items())
    except Exception:  # noqa: BLE001 - never break the scrape
        items = []
    for key, v in items:
        op, _, path = key.partition("[")
        tags = (("op", op),) + ((("path", path.rstrip("]")),) if path else ())
        _entry(entries, "m3_dispatch_ops_total", tags, now_ns, v)
    write_batch = getattr(db, "write_batch", None)
    if write_batch is not None:
        results = write_batch(namespace, entries)
        bad = [r for r in results if r is not None]
        if bad:  # scrape failures must stay loud, like the old raise
            raise RuntimeError(
                f"self-scrape: {len(bad)}/{len(entries)} samples failed "
                f"(first: {bad[0]})")
        return len(entries)
    for name, fields, t_ns, v in entries:
        db.write_tagged(namespace, name, fields, t_ns, v)
    return len(entries)


class SelfMonitor:
    """Tick-driven self-scrape for a service loop: call `maybe_scrape()`
    every tick; it scrapes when `interval_s` has elapsed."""

    def __init__(self, db, interval_s: float = 10.0,
                 namespace: str = SELF_NAMESPACE, registry=None,
                 clock=time.monotonic):
        self.db = db
        self.interval_s = interval_s
        self.namespace = namespace
        self.registry = registry or default_registry()
        self._clock = clock
        self._last = 0.0
        # anchor for cadence inference: time from construction to the
        # first maybe_scrape approximates the driver's tick interval
        self._last_call = clock()
        self.samples_written = 0
        self.enabled = ensure_namespace(db, namespace)
        self._hb = None

    def maybe_scrape(self, now_ns: int | None = None) -> int:
        if not self.enabled:
            return 0
        now = self._clock()
        # stall watchdog: a wedged self-scrape means the platform has
        # silently gone blind to itself. Registered LAZILY on the first
        # call and beaten per CALL, with the interval self-tuned to the
        # observed driving cadence — this monitor is ticked by the
        # coordinator loop, which may run slower than interval_s; a 1s
        # scrape interval under a 10s tick (or the construction-to-first-
        # tick gap) must never read as a stall, while a driver that
        # stops calling entirely still flags
        gap = now - self._last_call
        self._last_call = now
        if self._hb is None:
            from m3_tpu.utils import profiler

            self._hb = profiler.register_heartbeat(
                "selfscrape", max(self.interval_s, gap))
        else:
            self._hb.interval_s = max(self.interval_s, gap)
        self._hb.beat()
        if now - self._last < self.interval_s:
            return 0
        self._last = now
        n = scrape_once(self.db, self.registry, self.namespace, now_ns)
        self.samples_written += n
        return n

    def close(self) -> None:
        """Unregister the watchdog heartbeat (service shutdown) — a
        registered loop that will never beat again is a false stall."""
        if self._hb is not None:
            self._hb.close()
            self._hb = None
