"""The histogram/timer name catalog — the exposition contract.

Every literal name passed to ``Scope.observe`` / ``Scope.histogram`` /
``Scope.histogram_handle`` / ``Scope.timer`` in m3_tpu must be listed
here (m3lint rule ``inv-histogram-catalog``).  The catalog is what
dashboards, the self-scrape (`_m3_system`) queries, and the OpenMetrics
exemplar links are written against: a histogram that exists only at its
call site is a metric nobody can alert on, and a renamed one silently
breaks every recorded query.

Names are the LEAF names (the scope prefix supplies the subsystem, e.g.
``storage.db`` + ``write_batch_seconds``).  Keep the set literal — the
lint parses it with ``ast.literal_eval`` and never imports this module.
"""

from __future__ import annotations

HISTOGRAMS = {
    # storage / durability plane
    "write_seconds",            # storage.db per-point write
    "write_batch_seconds",      # storage.db fused batch write
    "write_batch_size",         # storage.db entries per batch
    "read_many_seconds",        # storage.ns fused batch read
    "shard_flush_seconds",      # shard warm flush
    "commitlog_fsync_seconds",  # WAL fsync wall time
    "persist_seconds",          # fileset/index/kv persist (per-scope)
    # compute plane
    "seconds",                  # decode/encode + rpc legs (per-scope);
    #                             also compute.execute{op,sig} — the
    #                             compute_execute_seconds exposition
    #                             family: wall time of one tracked
    #                             cache-HIT program call (dispatch +
    #                             device execution; sig is the
    #                             shape-bucket signature, <=64 distinct
    #                             labels then "other")
    "batch_size",               # decode.batch per-rung batch size
    "compile_seconds",          # compute.jit trace+compile on cache miss
    "plan_compile_seconds",     # compute.query_plan whole-plan compile
    #                             on a plan-shape cache miss (ROADMAP #2)
    # cluster / messaging plane
    "append_seconds",           # consensus append-entries
    "commit_seconds",           # consensus majority commit
    "send_seconds",             # msg producer
    "recv_seconds",             # msg consumer
    "http_seconds",             # storage peers HTTP
    "cycle_seconds",            # repair daemon anti-entropy cycle
    # client / query plane
    "fetch_many_seconds",       # session batched fetch
    "request_seconds",          # coordinator request + per-tenant SLO
    "flush_seconds",            # aggregator flush
    # pipelined dataflow (storage/pipeline)
    "stage_seconds",            # pipeline.stage{stage=gather|decode}:
    #                             per-run stage-time sums; compared with
    #                             the run's wall time they expose overlap
    # profiling & saturation plane (utils/profiler)
    "sample_seconds",           # profiler per-pass sampling wall time
    "wait_seconds",             # lock.wait_seconds{cls=site}: per-class
    #                             acquire-wait (published via
    #                             merge_histogram at snapshot time)
    # paged columnar memory & device-resident hot tier (ROADMAP #3)
    "page_fill",                # storage.page_pool: fraction of a sealed
    #                             window's page allocation holding real
    #                             rows (padding-waste measure, observed
    #                             at every ragged seal)
    "hot_tier_entry_bytes",     # storage.hot_tier: resident bytes of one
    #                             prepared-slab entry at admission
    # device-compiled inverted index (ROADMAP #4)
    "postings_seconds",         # compute.index: wall time of one fused
    #                             postings-program call (index/device.py;
    #                             a shape-cache miss includes compile —
    #                             compute.jit{op=postings_program} splits
    #                             hit/miss and compile time out)
    # standing-query plane (ROADMAP #2, query/standing.py)
    "rule_eval_lag_seconds",    # aggregator.standing: how far behind
    #                             real time a rule's last evaluated grid
    #                             point was when its re-evaluation
    #                             started (bounded-lag contract the
    #                             standing_rules rig episode audits)
}

TIMERS = {
    "tick",                     # coordinator/dbnode tick loops
}

# Non-histogram families the profiling & saturation plane exports —
# documented here so dashboards have one contract file to read (the
# lint only enforces the histogram/timer sets above):
#   queue_depth / queue_capacity / queue_dropped {queue=...}  gauges
#       refreshed at every registry snapshot (instrument.monitor_queue)
#   lock_acquisitions / lock_contended {cls=...}              counters
#   watchdog_loop_stalls {loop=...}                           counter
#   profiler_samples / profiler_evicted_samples               (status
#       JSON on /debug/profile; not registry families)
#
# Sharded compute plane (PR 12) mesh-dispatch counter families, under
# the compute.mesh scope with a {devices=N} label:
#   compute_mesh_dispatch {devices=...}        fused queries served on
#       the series-sharded device mesh (query/compiler._execute)
#   compute_mesh_skew_fallback {devices=...}   sharded dispatch declined
#       because the series->sample distribution was too skewed for
#       balanced slabs (ran the single-device program instead)
# plus the dispatch-layer tallies query.compile[sharded] and
# windowed_agg.aggregate_groups[mesh] on /debug counters.
#
# Paged columnar memory & device-resident hot tier (ROADMAP #3):
#   queue_depth/capacity/dropped {queue=page_pool}   pages in use /
#       pages resident / pages evicted back to the OS, aggregated over
#       every shard's pool (storage/pagepool.monitor_pool)
#   storage_page_pool_resident_bytes                 gauge refreshed by
#       the pagepool snapshot hook
#   queue_depth/capacity/dropped {queue=hot_tier}    prepared-slab bytes
#       used / byte cap / LRU evictions (storage/hottier)
#   storage_hot_tier_hit / storage_hot_tier_miss     per-query counters
#       (compiled path; the same outcome rides the ?explain=analyze
#       hot_tier block)
#
# Device-compiled inverted index (ROADMAP #4), compute.index scope:
#   compute_index_device                       segments whose boolean
#       postings algebra ran as ONE fused ragged program
#       (index/device.py match)
#   compute_index_fallback {reason=...}        segments that took the
#       counted scalar walk instead — reason is one of
#       unpacked_segment / nested_boolean / trivial_query /
#       jax_not_ready / small_work; the same split rides the
#       ?explain=analyze `index` block per query
# plus the dispatch-layer tallies index.postings[device|host] and
# jit_postings_program[hit|miss] on /debug counters.
#
# Topology elasticity (PR 17), placement scope — the off-tick handoff
# controller (services/handoff.py) and the client-plane placement
# watcher (client/topology_watch.py):
#   placement_sync_deferred {reason=...}       handoffs that could NOT
#       safely cut over this pass — reason is one of unreachable /
#       tail_flush_failed / digests_diverged / no_placement; each defer
#       also emits the placement.sync.defer tracepoint with the shard id
#   placement_cutover_failures                 mark_available CAS lost
#       (KV contention/outage); the shard re-enters the handoff lane on
#       the next placement sync
#   placement_handoff_errors                   a shard handoff aborted on
#       an unexpected error (retried next sync)
#   session_topology_version                   gauge: the placement KV
#       version the client session's TopologyMap was last hot-swapped
#       to; lag against the KV's own version is swap latency
#
# Standing-query plane (ROADMAP #2), aggregator.standing scope — one
# counter bump per rule per flush pass (query/standing.py evaluate):
#   aggregator_standing_rules_evaluated        rules whose invalidated
#       grid actually re-evaluated (compiled plan ran, outputs written)
#   aggregator_standing_rules_invalidated      rules whose input shards'
#       data_version bumps (or bootstrap/placement change) invalidated
#       their last evaluation key
#   aggregator_standing_rules_skipped          rules whose (data_version,
#       selector, grid) identity was unchanged — no sample reads, no
#       evaluation (the steady-state incremental win)
#   aggregator_standing_rules_errors           rule evaluations aborted
#       on an error (bad out-of-band expr, storage failure); the rule
#       retries next flush
#
# Device-compute observability plane (utils/compute_stats +
# dispatch.jit_tracker; the /debug/compute payload renders the same
# ledger as JSON on all four services):
#   compute_execute_seconds {op,sig}           histogram (the cataloged
#       "seconds" leaf under compute.execute) — the per-program
#       device-time attribution
#   compute_jit_cache_evictions {op}           counter: executable-cache
#       entries that vanished between tracked calls (clear_caches,
#       donated/evicted executables) — the miss-accounting ground truth
#   compute_waste_logical_elements /
#   compute_waste_padded_elements /
#   compute_waste_waste_ratio {site,axis}      gauges refreshed by the
#       compute_stats snapshot hook: real vs half-octave/slab-padded
#       elements at every padding seam (site in query_slabs / postings /
#       encode_ragged / decode_batch / windowed_agg)
#   compute_device_cache_* {cache=...}         gauges (entries, bytes,
#       bf16_bytes, ...) from registered device-resident cache
#       providers: the hot tier (storage/hottier) and the per-segment
#       postings columns (index/packed)
#   compute_profile_degraded {reason=...}      counter: static program
#       profile capture (lowered cost_analysis / memory_analysis)
#       unavailable on this backend — counted, never fatal; reason is
#       one of lower_failed / cost_failed / cost_unavailable /
#       memory_unavailable / profile_failed
#
# Tier-resolution read routing (query/resolver.resolve_read), query.tier
# scope with a {tier=...} label (raw / stitched / pinned_raw /
# aggregated_<res>s — bounded by distinct tier resolutions):
#   query_tier_reads {tier=...}                selector fetches served
#       by each tier choice; the same decision rides ?explain=analyze
#       as the per-fetch `tiers` block
#
# Binary wire plane (utils/wire, ROADMAP #1) — the bytes-on-wire ledger
# for the fat inter-node flows, counted by the CLIENT side of each flow
# (one unambiguous owner per counter: the coordinator accounts
# read_batch + response, a repairing/bootstrapping dbnode accounts
# stream_block + rollup); the rig surfaces the sums as the
# net_bytes_total trajectory column:
#   net_bytes_sent {flow=read_batch|stream_block|rollup|response}
#       request/response bytes written to the wire for that flow
#   net_bytes_recv {flow=...}                  bytes read off the wire
#   net_wire_fallback {reason=server_json|client_json}
#       a packed-capable side served/parsed legacy JSON instead
#       (mixed-version fleet); every bump also emits the wire.fallback
#       tracepoint — counted, never an error
