"""Named tracepoints + in-process span recording.

Role parity with the reference's OpenTracing plumbing
(/root/reference/src/dbnode/tracepoint/tracepoint.go named operation
constants, x/context StartSampledTraceSpan, x/opentracing/tracing.go): hot
paths open named spans that nest via a thread-local stack and land in a
bounded ring buffer exposed at /debug/traces. Sampling keeps the
steady-state cost to a perf_counter call; an OTLP-style exporter can drain
the ring without touching the serving path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

# tracepoint name constants (the tracepoint.go role)
DB_WRITE = "storage.db.write"
DB_QUERY = "storage.db.query"
INDEX_QUERY = "index.query"
SHARD_FLUSH = "storage.shard.flush"
ENGINE_QUERY = "query.engine.query_range"
SESSION_FETCH = "client.session.fetch_many"
AGG_FLUSH = "aggregator.flush"


@dataclass
class Span:
    name: str
    start_ns: int
    duration_ns: int = 0
    parent: str | None = None
    tags: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start_unix_ns": self.start_ns,
            "duration_us": round(self.duration_ns / 1000, 1),
            "parent": self.parent,
            **({"tags": self.tags} if self.tags else {}),
        }


class Tracer:
    """Bounded recorder; one per process (default_tracer())."""

    def __init__(self, capacity: int = 2048, sample_every: int = 1):
        self.capacity = capacity
        self.sample_every = max(1, sample_every)
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._tl = threading.local()
        self._lock = threading.Lock()
        self._counter = 0
        self.enabled = True

    def _stack(self) -> list:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
        return st

    @contextmanager
    def span(self, name: str, **tags):
        if not self.enabled:
            yield None
            return
        self._counter += 1  # racy increment is fine for sampling
        if self._counter % self.sample_every:
            yield None
            return
        stack = self._stack()
        parent = stack[-1] if stack else None
        sp = Span(name, time.time_ns(), parent=parent, tags=dict(tags))
        stack.append(name)
        t0 = time.perf_counter_ns()
        try:
            yield sp
        finally:
            sp.duration_ns = time.perf_counter_ns() - t0
            stack.pop()
            with self._lock:
                self._spans.append(sp)

    def recent(self, limit: int = 200) -> list[dict]:
        with self._lock:
            spans = list(self._spans)[-limit:]
        return [s.to_dict() for s in spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


_default = Tracer()


def default_tracer() -> Tracer:
    return _default


def span(name: str, **tags):
    """Open a span on the process tracer: `with trace.span(trace.DB_WRITE):`"""
    return _default.span(name, **tags)
