"""Distributed tracing: named tracepoints, span identity, W3C propagation.

Role parity with the reference's OpenTracing plumbing
(/root/reference/src/dbnode/tracepoint/tracepoint.go named operation
constants, x/context StartSampledTraceSpan, x/opentracing/tracing.go),
upgraded from process-local span recording to real distributed traces:

- every recorded Span carries (trace_id, span_id, parent_span_id), so a
  fan-out query stitches into ONE tree across coordinator, client session
  and storage nodes;
- the context propagates across processes as a W3C-`traceparent`-style
  header (``00-<trace_id>-<span_id>-<flags>``) on HTTP requests, as gRPC
  metadata on remote-zone/kvd RPCs, and as an envelope field on m3msg
  frames;
- the sampling decision is HEAD-BASED: made once at ingress
  (``start_request``) and honored by every downstream hop via the
  propagated flags bit, so a trace is never half-recorded;
- spans land in a bounded per-process ring exposed at /debug/traces; the
  coordinator's handler additionally gathers matching spans from its
  storage nodes and returns the stitched cross-process tree.

Steady-state cost: an unsampled request pays one thread-local read per
tracepoint; a disabled tracer pays one attribute check. The sampler is a
lock-free ``itertools.count`` (atomic under CPython), replacing the old
documented-racy ``_counter % sample_every`` increment.

``M3_TPU_TRACE_SAMPLE`` overrides the default tracer's sampling: ``0``
disables tracing, ``N`` samples one root trace in N.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

# tracepoint name constants (the tracepoint.go role). The observability
# check (tools/check_observability.py) asserts these values stay unique.
DB_WRITE = "storage.db.write"
DB_WRITE_BATCH = "storage.db.write_batch"
DB_QUERY = "storage.db.query"
INDEX_QUERY = "index.query"
SHARD_FLUSH = "storage.shard.flush"
ENGINE_QUERY = "query.engine.query_range"
SESSION_FETCH = "client.session.fetch_many"
AGG_FLUSH = "aggregator.flush"
READ_MANY = "storage.ns.read_many"
DECODE_BATCH = "storage.decode.batch"
DBNODE_HANDLE = "dbnode.handle"
API_REQUEST = "query.api.request"
FANOUT_READ = "query.fanout.read_many"
MSG_SEND = "msg.producer.send"
MSG_RECV = "msg.consumer.handle"
KVD_RPC = "kvd.client.rpc"
KVD_HANDLE = "kvd.server.handle"
PEER_HTTP = "storage.peer.http"
TENANT_SHED = "tenant.admission.shed"
REPAIR_CYCLE = "storage.repair.cycle"
QUERY_COMPILE_FALLBACK = "query.compile.fallback"
WATCHDOG_STALL = "watchdog.stall"
PLACEMENT_SYNC_DEFER = "placement.sync.defer"
WIRE_FALLBACK = "wire.fallback"

_ZERO_SPAN_ID = "0" * 16
# placeholder trace id carried by a negative head decision's context —
# never recorded, only propagated so descendants stay silent too
_UNSAMPLED_TRACE_ID = "f" * 32


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of the active span (or of the head sampling
    decision before any span opened: span_id == "" then)."""

    trace_id: str  # 32 hex chars (16 bytes)
    span_id: str   # 16 hex chars (8 bytes); "" = decision-only context
    sampled: bool = True

    def to_traceparent(self) -> str:
        return (f"00-{self.trace_id}-{self.span_id or _ZERO_SPAN_ID}-"
                f"{'01' if self.sampled else '00'}")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(value: str | None) -> SpanContext | None:
    """``00-<32 hex>-<16 hex>-<2 hex flags>`` -> SpanContext, else None.
    Unknown versions parse leniently (same field layout), per the W3C
    forward-compat rule; malformed values are ignored, never raised on."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if version == "ff" or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    if trace_id == "0" * 32:
        return None
    return SpanContext(trace_id, span_id, sampled)


@dataclass
class Span:
    name: str
    start_ns: int
    duration_ns: int = 0
    parent: str | None = None  # parent tracepoint NAME (legacy surface)
    tags: dict = field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str | None = None
    # ring admission order, monotonic per process — the exporter's drain
    # cursor (utils/export.py) ships each recorded span exactly once
    seq: int = 0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start_unix_ns": self.start_ns,
            "duration_us": round(self.duration_ns / 1000, 1),
            "parent": self.parent,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            **({"tags": self.tags} if self.tags else {}),
        }


class Tracer:
    """Bounded recorder; one per process (default_tracer()).

    Sampling: a tracepoint hit with NO active context is a trace root and
    draws a head decision from the lock-free counter (1-in-sample_every).
    A hit under an active context follows that context's decision — the
    ingress decides once, everything below (including remote hops that
    propagated the flags bit) honors it.
    """

    def __init__(self, capacity: int = 2048, sample_every: int = 1):
        self.capacity = capacity
        self.sample_every = max(1, sample_every)
        # only the PROCESS tracer's ring rides the saturation plane (the
        # module-level monitor_queue below); privately-constructed
        # tracers are test fixtures whose rings gauge nothing
        # m3lint: disable=inv-queue-gauge
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._tl = threading.local()
        self._lock = threading.Lock()
        # lock-free sampler: next() on itertools.count is atomic in
        # CPython (a single C call), unlike the old racy `_counter += 1`
        self._count = itertools.count()
        # ring admission counter (under _lock): export_since cursors
        self._last_seq = 0
        self.enabled = True

    def _stack(self) -> list:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
        return st

    # -- context plumbing --

    def current(self) -> SpanContext | None:
        """The active SpanContext on this thread (propagated or opened by
        an enclosing span), or None outside any trace."""
        return getattr(self._tl, "ctx", None)

    def sample_head(self) -> bool:
        """One head-based sampling decision (root of a new trace)."""
        if not self.enabled:
            return False
        return next(self._count) % self.sample_every == 0

    def start_request(self, headers=None) -> SpanContext:
        """Ingress context: honor a propagated ``traceparent`` if present,
        else mint a new root trace with a head sampling decision. Always
        returns a context (so the response can echo the trace id);
        `sampled=False` contexts make every downstream tracepoint a no-op.

        `headers` is any case-insensitive-ish mapping (http.client
        HTTPMessage, dict, or None)."""
        tp = None
        if headers is not None:
            get = getattr(headers, "get", None)
            if get is not None:
                tp = get("traceparent") or get("Traceparent")
        ctx = parse_traceparent(tp)
        if ctx is not None:
            return ctx
        return SpanContext(new_trace_id(), "", self.sample_head())

    @contextmanager
    def activate(self, ctx: SpanContext | None):
        """Install `ctx` as this thread's active context for the scope
        (server-side of a propagated hop)."""
        tl = self._tl
        prev = getattr(tl, "ctx", None)
        tl.ctx = ctx
        try:
            yield ctx
        finally:
            tl.ctx = prev

    def inject_headers(self, extra: dict | None = None) -> dict:
        """Headers carrying the active context ({} when none/disabled)."""
        ctx = self.current()
        out = dict(extra) if extra else {}
        if ctx is not None and self.enabled:
            out["traceparent"] = ctx.to_traceparent()
        return out

    # -- spans --

    @contextmanager
    def span(self, name: str, **tags):
        if not self.enabled:
            yield None
            return
        tl = self._tl
        ctx = getattr(tl, "ctx", None)
        if ctx is None:
            # trace root: head decision. A NEGATIVE decision still installs
            # a not-sampled context for the span's extent — descendant
            # tracepoints must follow this root's decision, not draw their
            # own (which would record orphan bottom-half trees)
            if next(self._count) % self.sample_every:
                tl.ctx = SpanContext(_UNSAMPLED_TRACE_ID, "", False)
                try:
                    yield None
                finally:
                    tl.ctx = None
                return
            trace_id = new_trace_id()
            parent_sid: str | None = None
        elif not ctx.sampled:
            yield None
            return
        else:
            trace_id = ctx.trace_id
            parent_sid = ctx.span_id or None
        sid = new_span_id()
        stack = self._stack()
        parent_name = stack[-1] if stack else None
        sp = Span(name, time.time_ns(), parent=parent_name, tags=dict(tags),
                  trace_id=trace_id, span_id=sid, parent_span_id=parent_sid)
        stack.append(name)
        prev_ctx = ctx
        tl.ctx = SpanContext(trace_id, sid, True)
        t0 = time.perf_counter_ns()
        try:
            yield sp
        finally:
            sp.duration_ns = time.perf_counter_ns() - t0
            stack.pop()
            tl.ctx = prev_ctx
            with self._lock:
                self._last_seq += 1
                sp.seq = self._last_seq
                self._spans.append(sp)

    # -- ring access --

    def recent(self, limit: int = 200) -> list[dict]:
        with self._lock:
            spans = list(self._spans)[-limit:]
        return [s.to_dict() for s in spans]

    def find(self, trace_id: str) -> list[dict]:
        """Every ring span belonging to `trace_id`, oldest first."""
        with self._lock:
            spans = [s for s in self._spans if s.trace_id == trace_id]
        return [s.to_dict() for s in spans]

    def export_since(self, cursor: int) -> tuple[list[dict], int]:
        """Spans recorded after `cursor` (a prior call's returned cursor;
        0 = everything still in the ring) plus the new cursor. The
        exporter's drain surface: spans evicted from the bounded ring
        between drains are simply gone — the ring never grows to wait for
        a slow exporter (export must not backpressure recording)."""
        with self._lock:
            spans = [s for s in self._spans if s.seq > cursor]
            last = self._last_seq
        return [s.to_dict() for s in spans], last

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


def build_tree(spans: list[dict]) -> list[dict]:
    """Nest span dicts into parent->children trees by span id. Spans whose
    parent_span_id is absent from the set become roots (the cross-process
    gather may be partial); duplicates (same span_id, e.g. a span served
    by both the local ring and a node's) dedupe, first occurrence wins."""
    by_id: dict[str, dict] = {}
    ordered: list[dict] = []
    for s in spans:
        sid = s.get("span_id") or ""
        if sid and sid in by_id:
            continue
        node = {**s, "children": []}
        if sid:
            by_id[sid] = node
        ordered.append(node)
    roots = []
    for node in ordered:
        parent = node.get("parent_span_id")
        if parent and parent in by_id and by_id[parent] is not node:
            by_id[parent]["children"].append(node)
        else:
            roots.append(node)
    return roots


def _env_sample() -> tuple[int, bool]:
    """(sample_every, enabled) from M3_TPU_TRACE_SAMPLE (0 disables)."""
    raw = os.environ.get("M3_TPU_TRACE_SAMPLE", "")
    if not raw:
        return 1, True
    try:
        n = int(raw)
    except ValueError:
        return 1, True
    if n <= 0:
        return 1, False
    return n, True


_sample_every, _enabled = _env_sample()
_default = Tracer(sample_every=_sample_every)
_default.enabled = _enabled

# the process span ring is a bounded buffer like any other: its depth
# rides the saturation plane (a full ring means the exporter is losing
# spans between drains)
from m3_tpu.utils import instrument as _instrument  # noqa: E402

_instrument.monitor_queue("trace_ring", lambda: len(_default._spans),
                          _default.capacity)


def default_tracer() -> Tracer:
    return _default


def span(name: str, **tags):
    """Open a span on the process tracer: `with trace.span(trace.DB_WRITE):`"""
    return _default.span(name, **tags)


def current() -> SpanContext | None:
    return _default.current()


def activate(ctx: SpanContext | None):
    return _default.activate(ctx)


def start_request(headers=None) -> SpanContext:
    return _default.start_request(headers)


def inject_headers(extra: dict | None = None) -> dict:
    return _default.inject_headers(extra)


def grpc_metadata() -> tuple | None:
    """The active context as gRPC metadata, or None outside a trace."""
    ctx = _default.current()
    if ctx is None or not _default.enabled:
        return None
    return (("traceparent", ctx.to_traceparent()),)


def from_grpc_context(grpc_ctx) -> SpanContext | None:
    """Extract a propagated context from a grpc.ServicerContext."""
    try:
        md = grpc_ctx.invocation_metadata()
    except Exception:  # noqa: BLE001 - non-grpc test doubles
        return None
    for key, value in md or ():
        if key == "traceparent":
            return parse_traceparent(value)
    return None
