"""Distributed query/aggregation kernels over the (shard x replica) mesh.

These are the XLA-collective replacements for the reference's network
fan-outs (SURVEY.md §2.11):

- cross-shard rollup: coordinator scatter/gather + aggregator forwarding
  (query/storage/m3/storage.go:286-496, aggregator forwarded_writer.go)
  becomes a local segment reduction + psum over the 'shard' ICI axis;
- replica divergence detection: the background repair's metadata checksum
  comparison (storage/repair.go:839) becomes an all_gather over 'replica'
  + elementwise compare, entirely device-resident;
- time-sharded windowed sums: long-range queries shard the time axis and
  exchange window-boundary partials with ppermute — the ring pattern
  (SURVEY.md §5 long-context analog) instead of materializing the range on
  one host.

All kernels are shard_map'd SPMD programs: jit once, run on every device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax import shard_map
except ImportError:  # jax<0.5 ships shard_map under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

import m3_tpu.ops  # noqa: F401  (x64)


def sharded_group_sum(values, group_ids, n_groups: int, mesh):
    """Global per-group (sum, count) of series sharded over 'shard'.

    values: [S, T] f64 sharded on S; group_ids: [S] int32 (global group
    space). Returns replicated [G, T] sums and [G] counts.
    """

    def local(values, group_ids):
        seg = jax.ops.segment_sum(values, group_ids, num_segments=n_groups)
        cnt = jax.ops.segment_sum(
            jnp.ones(values.shape[0], jnp.int32), group_ids, num_segments=n_groups
        )
        total = lax.psum(seg, "shard")
        count = lax.psum(cnt, "shard")
        if mesh.shape.get("replica", 1) > 1:
            # each replica already computed the exact global total (the
            # psum runs over 'shard' only); the pmean of identical values
            # just marks the result replicated over 'replica' for out_specs
            total = lax.pmean(total, "replica")
            count = lax.pmean(count, "replica")
        return total, count

    f = shard_map(
        local, mesh=mesh,
        in_specs=(P("shard", None), P("shard")),
        out_specs=(P(None, None), P(None)),
    )
    return f(values, group_ids)


def replica_divergence(series_checksums, mesh):
    """Detect replica divergence: [S] uint64 per-series block checksums,
    sharded on 'shard', replicated on 'replica'. Returns [S] bool sharded
    like the input: True where any replica disagrees (repair candidates)."""

    def local(cs):
        everyone = lax.all_gather(cs, "replica")  # [R, S_local]
        diverged = (everyone != everyone[0:1]).any(axis=0)
        # pmax makes the (already identical) result explicitly replicated
        # across 'replica' so the out_spec's replication is inferable
        return lax.pmax(diverged.astype(jnp.int32), "replica").astype(bool)

    f = shard_map(
        local, mesh=mesh, in_specs=(P("shard"),), out_specs=P("shard")
    )
    return f(series_checksums)


def time_sharded_window_sums(values, mesh, points_per_window: int):
    """Windowed sums over a time axis sharded across 'shard'.

    values: [S, T] with T sharded. Windows of `points_per_window` columns
    may straddle device boundaries; each device computes its local partial
    windows and the straddling head/tail partials ride a ppermute ring to
    the neighbor that owns the window start — the blockwise/ring pattern.
    Requires T % shard == 0. Returns [S, T // points_per_window] sums
    replicated across the mesh.
    """
    n_dev = mesh.shape["shard"]
    if values.shape[1] % points_per_window != 0:
        raise ValueError(
            f"time axis {values.shape[1]} not a multiple of window "
            f"{points_per_window} (trailing columns would be dropped)"
        )

    def local(vals):
        S, t_local = vals.shape
        idx = lax.axis_index("shard")
        t0 = idx * t_local  # global column offset of this device's slab
        w = points_per_window
        col = t0 + jnp.arange(t_local)
        wid = col // w  # global window id per local column
        n_windows_total = (t_local * n_dev) // w
        partial = jax.ops.segment_sum(
            vals.T, wid, num_segments=n_windows_total, indices_are_sorted=True
        ).T  # [S, W_total] local partials
        # windows are disjoint per column, so a psum combines straddling
        # partials exactly (each device contributed its own columns)
        return lax.psum(partial, "shard")

    f = shard_map(local, mesh=mesh, in_specs=(P(None, "shard"),),
                  out_specs=P(None, None))
    return f(values)


def ring_shift_boundary(values, mesh):
    """One ppermute ring step over 'shard': each device receives its left
    neighbor's last column (the boundary-exchange primitive used when a
    computation needs its predecessor's tail, e.g. delta-of-delta across a
    time-shard split)."""

    def local(vals):
        last_col = vals[:, -1:]
        n = mesh.shape["shard"]
        recv = lax.ppermute(
            last_col, "shard", [(i, (i + 1) % n) for i in range(n)]
        )
        return recv

    f = shard_map(local, mesh=mesh, in_specs=(P(None, "shard"),),
                  out_specs=P(None, "shard"))
    return f(values)


def time_sharded_reset_adjust(values, mesh):
    """Sequence-parallel counter monotonization: reset-adjust [S, T]
    counter samples whose TIME axis is sharded across 'shard'.

    The single-host form (query/windows._reset_adjusted, upstream
    Prometheus counter semantics) is a prefix computation over time —
    exactly the dependency ring/blockwise attention breaks for long
    sequences. Device-local work is one pass; the cross-device carry needs
    two tiny collectives (SURVEY.md §5 long-context analog):

      1. each device receives its LEFT neighbor's last column (ppermute
         ring) so a reset straddling the shard boundary is detected;
      2. per-device total drops all_gather into an EXCLUSIVE prefix over
         the mesh axis — the carry every device adds to its local
         cumulative drops.

    Returns the globally monotonized [S, T] matrix, sharded like the
    input. rate()/increase() over any window then reduces to
    last-minus-first regardless of which devices hold the window.
    """
    n = mesh.shape["shard"]

    def local(vals):
        # 1) boundary exchange: left neighbor's last column
        prev_col = lax.ppermute(
            vals[:, -1:], "shard", [(i, (i + 1) % n) for i in range(n)]
        )
        idx = lax.axis_index("shard")
        # device 0 has no predecessor: its first column can't be a reset
        prev = jnp.where(idx == 0, vals[:, :1], prev_col)
        shifted = jnp.concatenate([prev, vals[:, :-1]], axis=1)
        drop = jnp.where(vals < shifted, shifted, 0.0)
        local_cum = jnp.cumsum(drop, axis=1)
        # 2) exclusive prefix of per-device drop totals over the mesh axis
        totals = lax.all_gather(local_cum[:, -1], "shard")  # [n, S]
        mask = (jnp.arange(n) < idx)[:, None]
        carry = jnp.sum(totals * mask, axis=0)  # [S]
        return vals + local_cum + carry[:, None]

    f = shard_map(local, mesh=mesh, in_specs=(P(None, "shard"),),
                  out_specs=P(None, "shard"))
    return f(values)
