"""Device meshes from placements, and the series-sharded compute mesh.

The cluster placement's shard->instance assignment (m3_tpu.cluster.placement)
is the same partitioning the device mesh uses: the 'shard' axis carries M3's
data-parallel virtual shards, and the 'replica' axis carries RF copies
(SURVEY.md §2.10). Collectives over these axes replace the reference's
host-side scatter-gather RPC (§2.11): psum over ICI for cross-shard rollups,
all_gather over 'replica' for divergence checks.

The COMPUTE mesh (PR 12, ROADMAP #1) is the 1-D ``("series",)`` mesh the
whole-query compiler and the device aggregation kernels serve on:
series-major arrays shard their row axis across it with
``NamedSharding``/``PartitionSpec`` and grouped reductions lower to
psums over the series axis. Mesh and sharding objects are built ONCE per
(devices, spec) through the lru_cache factories below — per-eval
construction is the jax-jit-per-call hazard m3lint flags (a fresh Mesh
defeats jit's C++ dispatch fast path and risks minting fresh executable
cache keys).
"""

from __future__ import annotations

import functools
import os
import sys

import numpy as np


def build_mesh(n_shard: int, n_replica: int = 1, devices=None):
    """(shard x replica) mesh over the first n_shard*n_replica devices.

    Setup-time factory (dry runs, tests, placement wiring) — the per-eval
    serving plane goes through the cached ``compute_mesh`` instead."""
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    need = n_shard * n_replica
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_shard, n_replica)
    # m3lint: disable=jax-jit-per-call  (one-shot setup factory, not per-eval)
    return Mesh(grid, axis_names=("shard", "replica"))


# ---------------------------------------------------------------------------
# series-sharded compute mesh (the engine's serving plane)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def compute_mesh(n_devices: int):
    """The 1-D ``("series",)`` mesh over the first n_devices local devices
    — ONE Mesh object per device count for the life of the process, so
    every jit keyed on it reuses its executables."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    n = max(1, min(n_devices, len(devices)))
    return Mesh(np.array(devices[:n]), axis_names=("series",))


@functools.lru_cache(maxsize=None)
def row_sharding(mesh):
    """[S, T] series-major matrices: rows sharded, steps replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec("series", None))


@functools.lru_cache(maxsize=None)
def vec_sharding(mesh):
    """[S] per-series vectors (group ids, checksums): sharded."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec("series"))


@functools.lru_cache(maxsize=None)
def replicated_sharding(mesh):
    """Post-aggregation [G, T] outputs and small broadcast inputs."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def active_compute_mesh():
    """The compute mesh the serving paths should shard over, or None.

    ``M3_TPU_QUERY_SHARD`` is the operator hatch: ``0`` disables, an
    integer pins the device count (``1`` is a valid single-device mesh —
    the device-count-independence proof target), any other truthy value
    means all local devices. Unset, the mesh activates only when an
    accelerator backend with more than one device is ALREADY live
    (dispatch._accelerator_present discipline — reading the mesh must
    never be the thing that triggers PJRT init, which can wedge on a
    dead TPU tunnel), so single-device CPU behavior is unchanged."""
    spec = os.environ.get("M3_TPU_QUERY_SHARD", "").strip()
    if spec == "0":
        return None
    if spec:
        if "jax" not in sys.modules:
            return None
        try:
            n = int(spec)
        except ValueError:
            import jax

            n = len(jax.devices())
        return compute_mesh(n)
    from m3_tpu.utils import dispatch

    if not dispatch._accelerator_present():
        return None
    import jax

    n = len(jax.devices())
    return compute_mesh(n) if n > 1 else None


def mesh_from_placement(placement, devices=None):
    """Mesh whose 'shard' axis size matches the placement's distinct shard
    groups: device i takes the shards of the i-th instance (sorted)."""
    n_instances = len(placement.instances)
    rf = placement.replica_factor
    # mirrored/replicated placements: shard groups = instances / RF
    n_shard_groups = max(n_instances // rf, 1)
    return build_mesh(n_shard_groups, max(rf, 1), devices)
