"""Device meshes from placements.

The cluster placement's shard->instance assignment (m3_tpu.cluster.placement)
is the same partitioning the device mesh uses: the 'shard' axis carries M3's
data-parallel virtual shards, and the 'replica' axis carries RF copies
(SURVEY.md §2.10). Collectives over these axes replace the reference's
host-side scatter-gather RPC (§2.11): psum over ICI for cross-shard rollups,
all_gather over 'replica' for divergence checks.
"""

from __future__ import annotations

import numpy as np


def build_mesh(n_shard: int, n_replica: int = 1, devices=None):
    """(shard x replica) mesh over the first n_shard*n_replica devices."""
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    need = n_shard * n_replica
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_shard, n_replica)
    return Mesh(grid, axis_names=("shard", "replica"))


def mesh_from_placement(placement, devices=None):
    """Mesh whose 'shard' axis size matches the placement's distinct shard
    groups: device i takes the shards of the i-th instance (sorted)."""
    n_instances = len(placement.instances)
    rf = placement.replica_factor
    # mirrored/replicated placements: shard groups = instances / RF
    n_shard_groups = max(n_instances // rf, 1)
    return build_mesh(n_shard_groups, max(rf, 1), devices)
