"""Cluster environment manager: per-host agents + a deployment orchestrator.

The reference's m3em runs an agent on every test host that places builds
and configs, starts/stops node processes, and heartbeats back to a dtest
orchestrator (/root/reference/src/m3em/{agent,node,cluster}, gRPC
control). This is that role for this framework: an HTTP agent that
manages service processes in a working directory, and a ClusterEnv
orchestrator that drives N agents to deploy, exercise, and tear down a
multi-process cluster (the dtest tier — src/cmd/tools/dtest).

Design choices vs the reference:
- HTTP control plane (this framework's transport everywhere else); the
  agent surface is the same verbs: place file, start, stop, status,
  heartbeat, teardown.
- agents only launch `sys.executable -m <module> -f <config>` for an
  allow-listed set of service modules — the dtest harness places CONFIGS,
  not builds (one shared checkout; the reference places binaries because
  its hosts are remote machines).

Agent CLI:  python -m m3_tpu.tools.em --listen 127.0.0.1:0 --workdir DIR
The chosen port is printed to stdout and written to DIR/agent.port so
orchestrators spawning agents with port 0 can discover them.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ALLOWED_MODULES = (
    "m3_tpu.services.dbnode",
    "m3_tpu.services.coordinator",
    "m3_tpu.services.aggregator",
    "m3_tpu.cluster.kvd",
)


class _Managed:
    """One service process under agent management."""

    def __init__(self, name: str, module: str, config_path: str, env: dict,
                 workdir: str):
        self.name = name
        self.module = module
        self.config_path = config_path
        self.env = env
        self.workdir = workdir
        self.proc: subprocess.Popen | None = None
        self.started_at = 0.0
        self.log_path = os.path.join(workdir, f"{name}.log")

    def start(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError(f"service {self.name} already running")
        env = dict(os.environ)
        env.update(self.env)
        log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", self.module, "-f", self.config_path],
            cwd=self.workdir, env=env, stdout=log, stderr=log,
            start_new_session=True,
        )
        log.close()
        self.started_at = time.time()

    def stop(self, sig: int = signal.SIGTERM, timeout_s: float = 10.0) -> int | None:
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            self.proc.send_signal(sig)
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        return self.proc.returncode

    def status(self) -> dict:
        running = self.proc is not None and self.proc.poll() is None
        return {
            "name": self.name,
            "module": self.module,
            "running": running,
            "pid": self.proc.pid if running else None,
            "returncode": None if running or self.proc is None else self.proc.returncode,
            "uptime_s": round(time.time() - self.started_at, 1) if running else 0.0,
        }


class EmAgent:
    """HTTP process-manager agent for one host/workdir."""

    def __init__(self, workdir: str, listen: str = "127.0.0.1:0",
                 agent_id: str = ""):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.agent_id = agent_id or os.path.basename(self.workdir)
        self.services: dict[str, _Managed] = {}
        self._lock = threading.Lock()
        agent = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def do_GET(self):
                try:
                    self._send(*agent.handle("GET", self.path, b""))
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": str(e)})

            def do_POST(self):
                try:
                    self._send(*agent.handle("POST", self.path, self._body()))
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": str(e)})

            def do_PUT(self):
                try:
                    self._send(*agent.handle("PUT", self.path, self._body()))
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": str(e)})

        host, port = listen.rsplit(":", 1)
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        with open(os.path.join(self.workdir, "agent.port"), "w") as f:
            f.write(str(self.port))

    # -- request routing (method, path, body) -> (code, doc) --

    def handle(self, method: str, path: str, body: bytes):
        parts = [p for p in path.split("?")[0].split("/") if p]
        if method == "GET" and parts == ["health"]:
            with self._lock:
                return 200, {
                    "agent_id": self.agent_id,
                    "now": time.time(),
                    "services": {n: m.status() for n, m in self.services.items()},
                }
        if method == "PUT" and len(parts) == 2 and parts[0] == "files":
            name = os.path.basename(parts[1])  # no traversal
            with open(os.path.join(self.workdir, name), "wb") as f:
                f.write(body)
            return 200, {"placed": name, "bytes": len(body)}
        if method == "POST" and len(parts) == 3 and parts[0] == "services":
            name = parts[1]
            doc = json.loads(body.decode() or "{}")
            if parts[2] == "start":
                with self._lock:
                    prior = self.services.get(name)
                    # Placed state is sticky across restarts (the reference
                    # m3em agent relaunches from the placed build+config:
                    # src/m3em/agent): a restart request that omits module/
                    # config/env reuses what the service was first started
                    # with; only explicitly-provided non-empty values
                    # override.
                    module = doc.get("module") or (prior.module if prior else None)
                    if module not in ALLOWED_MODULES:
                        return 400, {"error": f"module {module!r} not allowed"}
                    config = (
                        os.path.join(self.workdir, os.path.basename(doc["config"]))
                        if doc.get("config")
                        else (prior.config_path if prior else None)
                    )
                    if config is None:
                        return 400, {"error": "start needs a config"}
                    env = doc.get("env") or (prior.env if prior else {})
                    if prior is not None and prior.proc is not None \
                            and prior.proc.poll() is None:
                        # idempotent start: re-asserting the SAME placement
                        # is a no-op success (orchestrators retry starts);
                        # only a conflicting module/config on a live
                        # service is an error
                        req_env = doc.get("env")
                        if module == prior.module \
                                and config == prior.config_path \
                                and (not req_env or req_env == prior.env):
                            return 200, prior.status()
                        return 409, {"error": f"service {name} already "
                                     "running with different "
                                     "module/config/env"}
                    m = _Managed(name, module, config, env, self.workdir)
                    self.services[name] = m
                    m.start()
                    return 200, m.status()
            if parts[2] == "stop":
                with self._lock:
                    m = self.services.get(name)
                if m is None:
                    return 404, {"error": f"unknown service {name}"}
                rc = m.stop(getattr(signal, doc.get("signal", "SIGTERM")))
                return 200, {"stopped": name, "returncode": rc}
        if method == "GET" and len(parts) == 3 and parts[0] == "services":
            name = parts[1]
            with self._lock:
                m = self.services.get(name)
            if m is None:
                return 404, {"error": f"unknown service {name}"}
            if parts[2] == "status":
                return 200, m.status()
            if parts[2] == "logs":
                try:
                    with open(m.log_path, "rb") as f:
                        f.seek(0, 2)
                        size = f.tell()
                        f.seek(max(0, size - 65536))
                        tail = f.read().decode(errors="replace")
                except OSError:
                    tail = ""
                return 200, {"log": tail}
        if method == "POST" and parts == ["teardown"]:
            self.teardown_services()
            return 200, {"stopped": "all"}
        return 404, {"error": f"no route {method} {path}"}

    def teardown_services(self) -> None:
        with self._lock:
            managed = list(self.services.values())
        for m in managed:
            m.stop()

    def close(self) -> None:
        self.teardown_services()
        self._server.shutdown()


class AgentError(RuntimeError):
    """An agent request failed; the message carries the agent's error
    document (and, for startup deaths, the child's log tail)."""


class AgentClient:
    """Orchestrator-side handle to one agent."""

    def __init__(self, endpoint: str, timeout_s: float = 15.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def _req(self, method: str, path: str, body: bytes = b"") -> dict:
        import urllib.error

        req = urllib.request.Request(self.endpoint + path, data=body or None,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            # surface the agent's error doc instead of a bare HTTP status
            detail = e.read().decode(errors="replace")[:4096]
            raise AgentError(
                f"agent {method} {path} -> {e.code}: {detail}") from None

    def health(self) -> dict:
        return self._req("GET", "/health")

    def put_file(self, name: str, content: str | bytes) -> dict:
        if isinstance(content, str):
            content = content.encode()
        return self._req("PUT", f"/files/{name}", content)

    def start(self, service: str, module: str | None = None,
              config: str | None = None, env: dict | None = None,
              grace_s: float = 1.0) -> dict:
        """Start (or restart) a service. All of module/config/env may be
        omitted on restart — the agent reuses the service's placed state.

        The startup grace window: after the agent acks the start, poll
        the child for `grace_s`; a process that dies inside the window
        (bad config, crashed import) raises AgentError carrying its exit
        code AND a log tail — the alternative is the orchestrator's
        wait_until looping to a timeout with no diagnostic. Raise the
        window for services whose failure mode is post-import (config
        parsing happens seconds into a JAX-importing boot); 0 skips the
        check entirely."""
        doc = {}
        if module:
            doc["module"] = module
        if config:
            doc["config"] = config
        if env:
            doc["env"] = env
        body = json.dumps(doc).encode()
        out = self._req("POST", f"/services/{service}/start", body)
        deadline = time.time() + grace_s
        while time.time() < deadline:
            st = self.status(service)
            if not st["running"]:
                tail = ""
                try:
                    tail = self.logs(service)[-4000:]
                except Exception:  # noqa: BLE001 - diagnostics best-effort
                    pass
                raise AgentError(
                    f"service {service} exited rc={st.get('returncode')} "
                    f"within {grace_s:.1f}s of start\n"
                    f"--- {service} log tail ---\n{tail}")
            time.sleep(min(0.1, grace_s))
        return out

    def stop(self, service: str, sig: str = "SIGTERM") -> dict:
        return self._req("POST", f"/services/{service}/stop",
                         json.dumps({"signal": sig}).encode())

    def kill(self, service: str) -> dict:
        """SIGKILL + reap: the chaos rig's kill-schedule primitive. The
        agent's stop path waits on the child, so by return the process
        is dead and its returncode recorded (no TERM grace, no cleanup —
        exactly the failure a production node loss is)."""
        return self.stop(service, sig="SIGKILL")

    def status(self, service: str) -> dict:
        return self._req("GET", f"/services/{service}/status")

    def logs(self, service: str) -> str:
        return self._req("GET", f"/services/{service}/logs")["log"]

    def teardown(self) -> dict:
        return self._req("POST", "/teardown")


class ClusterEnv:
    """Deployment orchestrator over named agents (the m3em cluster +
    dtest harness role)."""

    def __init__(self, agents: dict[str, AgentClient]):
        self.agents = agents

    def heartbeats(self) -> dict[str, dict]:
        out = {}
        for name, agent in self.agents.items():
            try:
                out[name] = agent.health()
            except Exception as e:  # noqa: BLE001 - a dead agent IS the signal
                out[name] = {"error": str(e)}
        return out

    def deploy_kvd_quorum(self, ports: dict[str, int],
                          service: str = "kvd",
                          env: dict | None = None) -> str:
        """Deploy an N-node quorum kvd metadata plane, one replica per
        named agent (N should be odd; {agent_name: port}). Each agent gets
        a config naming ITSELF in the shared peer set, so the replicas
        elect a leader among themselves and followers hint clients to it.
        ``env`` rides each start (e.g. PYTHONPATH / fault specs for chaos
        runs). Returns the comma-separated client target list (hand it to
        KvdClient / kv_addr). Kill any replica with
        ``stop(service, sig="SIGKILL")`` — the survivors re-elect and the
        restarted process rejoins from its raft journal."""
        peers = {name: f"127.0.0.1:{port}" for name, port in ports.items()}
        peer_spec = ",".join(f"{n}={a}" for n, a in peers.items())
        for name in ports:
            agent = self.agents[name]
            agent.put_file("kvd.yml", (
                f"kvd:\n  listen: {peers[name]}\n"
                f"  journal: kvd.{name}.journal\n"
                f"  node_id: {name}\n"
                f"  peers: {peer_spec}\n"))
            agent.start(service, "m3_tpu.cluster.kvd", "kvd.yml", env=env)
        return ",".join(peers.values())

    def teardown(self) -> None:
        for agent in self.agents.values():
            try:
                agent.teardown()
            except Exception:  # noqa: BLE001 - best effort on the way down
                pass

    @staticmethod
    def wait_until(fn, timeout_s: float = 30.0, every_s: float = 0.25,
                   desc: str = "condition"):
        """Poll fn() until truthy; raises TimeoutError with desc."""
        deadline = time.time() + timeout_s
        last_err = None
        while time.time() < deadline:
            try:
                out = fn()
                if out:
                    return out
            except Exception as e:  # noqa: BLE001 - keep polling
                last_err = e
            time.sleep(every_s)
        raise TimeoutError(f"timed out waiting for {desc}: {last_err}")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="cluster env manager agent")
    ap.add_argument("--listen", default="127.0.0.1:0")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--agent-id", default="")
    args = ap.parse_args(argv)
    agent = EmAgent(args.workdir, args.listen, args.agent_id)
    print(f"agent {agent.agent_id} listening on port {agent.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agent.close()


if __name__ == "__main__":
    main()
