"""BASELINE.md configs #1-#5 as one harness.

Prints one JSON line per config (same shape as bench.py). Sizes are
env-tunable; defaults are sized to finish on CPU in a few minutes —
on a real TPU set M3_BENCH_SCALE=1 for the full north-star shapes.

    python -m m3_tpu.tools.bench_all [--configs 1,2,3,4,5]

Baselines: the native C++ codec for #1 (same as bench.py); the HOST numpy
implementations of the same computation for #2/#3/#5 (dispatch-forced), so
vs_baseline is the device-vs-host speedup; pure-Python re.fullmatch vocab
scan for #4 (what a naive engine would do).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _scale() -> float:
    try:
        return float(os.environ.get("M3_BENCH_SCALE", "0.1"))
    except ValueError:
        return 0.1


def _emit(metric: str, dp_per_sec: float, baseline: float) -> None:
    print(json.dumps({
        "metric": metric,
        "value": round(dp_per_sec / 1e6, 3),
        "unit": "M datapoints/sec",
        "vs_baseline": round(dp_per_sec / baseline, 3) if baseline else 0.0,
    }), flush=True)


def _time(fn, iters=3):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def _block(out):
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass


def _accelerator() -> bool:
    import jax

    return jax.default_backend() not in ("cpu",)


def config1_codec_roundtrip():
    """100k-series M3TSZ round-trip on the serving path vs the frozen v1
    scalar C++ baseline (the Go-hot-loop stand-in) — same methodology as
    bench.py: XLA codec on an accelerator, native v2 batch codec on CPU."""
    from __graft_entry__ import _example_batch
    from m3_tpu.encoding.m3tsz import native
    from m3_tpu.utils.xtime import TimeUnit

    B = max(int(100_000 * _scale()), 1024)
    T = 120
    times, vbits, start, n_points = _example_batch(B=B, T=T)
    values = vbits.view(np.float64)

    if _accelerator():
        import jax.numpy as jnp

        from m3_tpu.encoding.m3tsz import tpu

        jt, jv = jnp.asarray(times), jnp.asarray(vbits)
        js, jn = jnp.asarray(start), jnp.asarray(n_points)
        cap = (64 + 80 * T + 11 + 63) // 64

        def run():
            blocks = tpu.encode_bits(jt, jv, js, jn, TimeUnit.SECOND, cap)
            dec = tpu.decode(blocks.words, TimeUnit.SECOND, max_points=T)
            return blocks.words, dec.times

        dt = _time(run)
        rate = B * T / dt
        path = "xla device"
    elif native.available():
        native.bench_roundtrip_batch(times, values, int(start[0]),
                                     TimeUnit.SECOND)  # warm
        rates = [native.bench_roundtrip_batch(times, values, int(start[0]),
                                              TimeUnit.SECOND)[0]
                 for _ in range(3)]
        rate = sum(rates) / len(rates)
        path = f"native batch, {native.default_threads()}t"
    else:
        _emit(f"#1 m3tsz roundtrip {B}x{T} (no serving codec)", 0.0, 10e6)
        return
    base = None
    if native.available():
        base = native.bench_roundtrip(
            times[:4000], values[:4000], int(start[0]), TimeUnit.SECOND)
    _emit(f"#1 m3tsz roundtrip {B}x{T} [{path}]", rate, base or 10e6)


def config2_rollup():
    """1M-series counter+gauge rollup 10s -> 1m (device vs host numpy)."""
    from m3_tpu.ops import windowed_agg

    n = max(int(6_000_000 * _scale()), 100_000)  # 1M series x 6 samples
    rng = np.random.default_rng(0)
    n_series = n // 6
    e = rng.integers(0, n_series, n)
    w = rng.integers(0, 6, n)
    v = rng.normal(100, 10, n)
    t = rng.integers(0, 10**9, n)

    os.environ["M3_TPU_DEVICE_OPS"] = "1"
    dt_dev = _time(lambda: windowed_agg.aggregate_groups(e, w, v, times=t)[2]["sum"])
    os.environ["M3_TPU_DEVICE_OPS"] = "0"
    dt_host = _time(lambda: windowed_agg.aggregate_groups(e, w, v, times=t)[2]["sum"])
    os.environ.pop("M3_TPU_DEVICE_OPS", None)
    _emit(f"#2 rollup {n} samples -> {n_series} series", n / dt_dev,
          n / dt_host)


def config3_promql_rate_sum(tmp=None):
    """PromQL rate()+sum by() over a wide fetch (device vs host temporal)."""
    from m3_tpu.query.windows import NS, RaggedSeries
    from m3_tpu.query import windows

    S = max(int(100_000 * _scale()), 4_000)
    T = 240  # 1h at 15s
    per = []
    rng = np.random.default_rng(1)
    base_t = np.arange(T, dtype=np.int64) * 15 * NS
    for s in range(S):
        v = rng.integers(1, 10, T).astype(np.float64).cumsum()
        per.append((base_t, v))
    raws = RaggedSeries.from_lists(per)
    eval_ts = np.arange(300, 3600, 60, dtype=np.int64) * NS
    n_dp = S * T

    os.environ["M3_TPU_DEVICE_OPS"] = "1"
    dt_dev = _time(lambda: windows.extrapolated_rate(raws, eval_ts, 300 * NS,
                                                     True, True))
    os.environ["M3_TPU_DEVICE_OPS"] = "0"
    dt_host = _time(lambda: windows.extrapolated_rate(raws, eval_ts, 300 * NS,
                                                      True, True))
    os.environ.pop("M3_TPU_DEVICE_OPS", None)
    _emit(f"#3 rate() {S} series x {T} pts", n_dp / dt_dev, n_dp / dt_host)


def config4_regex_postings():
    """High-cardinality regex queries over packed postings vs naive scan."""
    import re

    from m3_tpu.index import packed
    from m3_tpu.index.segment import Document

    n = max(int(10_000_000 * _scale()), 200_000)
    docs = [Document(i, b"s%08d" % i, [(b"pod", b"pod-%08d" % i)])
            for i in range(n)]
    seg = packed.build(docs)
    pats = [rb"pod-0000\d\d\d\d", rb"pod-000[0-4]\d+", rb"pod-.*99",
            rb"pod-0(1|2)\d+", rb"pod-00001[0-9]{3}"]
    pats = (pats * 10)[:50]

    def run_packed():
        total = 0
        for p in pats:
            seg._regex_cache.clear()
            total += len(seg.postings_regexp(b"pod", re.compile(p)))
        return total

    t0 = time.perf_counter()
    run_packed()
    dt = time.perf_counter() - t0
    # naive baseline: per-term fullmatch of ONE pattern, extrapolated to 50
    terms = seg.terms(b"pod")[: min(n, 200_000)]
    rx = re.compile(pats[0])
    t0 = time.perf_counter()
    sum(1 for t in terms if rx.fullmatch(t))
    naive_per_query = (time.perf_counter() - t0) * (n / len(terms))
    _emit(f"#4 50 regex queries over {n}-term postings",
          50 * n / dt, 50 * n / (50 * naive_per_query))


def config5_sharded_quantile():
    """4-shard timer quantile rollup with explicit cross-shard psum.

    The device program is the flagship ICI pattern: shard_map over the
    mesh, per-shard selection-based quantile (top_k, NOT a full sort — a
    p99 over a T-point window needs only the top T-ceil(0.99 T) elements)
    + local segment sums, then one psum pair across the shard axis. The
    host baseline is the same computation in numpy (np.partition + add.at,
    also selection-based — no strawman)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import m3_tpu.ops  # noqa: F401  (x64)

    shard_map = jax.shard_map if hasattr(jax, "shard_map") else None
    if shard_map is None:  # older jax
        from jax.experimental.shard_map import shard_map

    n_dev = min(4, len(jax.devices()))
    devices = np.array(jax.devices()[:n_dev])
    mesh = Mesh(devices, axis_names=("shard",))
    S = max(int(10_000_000 * _scale()) // 64, 4096)
    S -= S % n_dev
    T = 64
    G = 128
    rng = np.random.default_rng(2)
    vals = rng.gamma(2.0, 10.0, (S, T))
    gids = (np.arange(S) % G).astype(np.int32)
    q_idx = int(T * 0.99)
    k = T - q_idx  # selection depth: sorted[q_idx] == k-th largest

    def kth_largest(v, kk):
        # iterative masked-max selection over the TIME axis of the
        # time-major [T, S] elem grid: kk-1 passes peel the larger
        # elements, pass kk's max is the answer. O(kk*T) elementwise — no
        # sort, no top_k (XLA:CPU lowers top_k to a full variadic sort;
        # TPU tiles elementwise reductions onto the VPU directly). The
        # time-major layout makes each reduction a vertical SIMD op across
        # series lanes instead of a horizontal within-row reduce (~6x on
        # XLA:CPU; same orientation the TPU VPU prefers with series on the
        # 128-lane axis).
        for _ in range(kk - 1):
            m = jnp.max(v, axis=0, keepdims=True)
            # mask exactly one occurrence of the max per series
            first = jnp.cumsum(v == m, axis=0) == 1
            v = jnp.where(first & (v == m), -jnp.inf, v)
        return jnp.max(v, axis=0)

    # group counts depend only on the shard->group placement, not on the
    # flushed values: precompute once (the host baseline likewise only
    # does the per-flush work — partition + scatter-add — in its timed
    # section)
    cnt_host = np.bincount(gids, minlength=G).astype(np.float64)

    def per_shard(v, g, cnt):
        q = kth_largest(v, k)
        seg = jax.ops.segment_sum(q, g, num_segments=G)
        seg = jax.lax.psum(seg, "shard")
        return seg / cnt

    quantile_rollup = jax.jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, "shard"), P("shard"), P()), out_specs=P(),
    ))

    # the device elem grid is stored time-major [T, S] (layout is ours to
    # choose for device-resident state); the host baseline keeps its own
    # best layout (row-major [S, T] for np.partition)
    jv = jax.device_put(jnp.asarray(vals.T.copy()),
                        jax.NamedSharding(mesh, P(None, "shard")))
    jg = jax.device_put(jnp.asarray(gids), jax.NamedSharding(mesh, P("shard")))
    jc = jax.device_put(jnp.asarray(np.maximum(cnt_host, 1.0)),
                        jax.NamedSharding(mesh, P()))
    dt = _time(lambda: quantile_rollup(jv, jg, jc))

    # host numpy baseline of the same computation
    def host():
        q = np.partition(vals, q_idx, axis=1)[:, q_idx]
        out = np.zeros(G)
        np.add.at(out, gids, q)
        return out

    t0 = time.perf_counter()
    for _ in range(3):
        host()
    dt_host = (time.perf_counter() - t0) / 3
    # correctness: device result == host result
    dev = np.asarray(quantile_rollup(jv, jg, jc))
    ok = np.allclose(dev, host() / np.maximum(cnt_host, 1), rtol=1e-9)
    _emit(f"#5 {n_dev}-shard timer quantile rollup {S}x{T}"
          + ("" if ok else " (CORRECTNESS FAILED)"),
          S * T / dt, S * T / dt_host)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5")
    args = ap.parse_args(argv)
    fns = {"1": config1_codec_roundtrip, "2": config2_rollup,
           "3": config3_promql_rate_sum, "4": config4_regex_postings,
           "5": config5_sharded_quantile}
    for c in args.configs.split(","):
        c = c.strip()
        try:
            fns[c]()
        except Exception as e:  # noqa: BLE001 - one config must not kill the rest
            print(json.dumps({"metric": f"#{c} failed: {e}"[:200],
                              "value": 0.0, "unit": "M datapoints/sec",
                              "vs_baseline": 0.0}), flush=True)


if __name__ == "__main__":
    main()
