"""BASELINE.md configs #1-#5 as one harness, plus #6 (the batched
read_many path — config #3's fetch leg measured directly), #7 (the
write-hot-path observability overhead guard), #8 (the batched
write_batch ingest path vs the per-entry loop), #9 (end-to-end
query_range latency, whole-query-compiled vs interpreted), #10 (the
profiler-overhead guard: sampling profiler + lock-wait profiling +
stall watchdog armed vs off, same pairing discipline as #7), #11
(the sharded query plane: the same fused query_range + grouped
aggregation on the series-sharded device mesh vs single-device, swept
over device counts), #12 (the pipelined dataflow: sparse
multi-group read_many->query e2e, executor-pipelined vs the pinned
serial seed path, pair-median, correctness-gated) and #14 (the
device-compiled inverted index: boolean matcher evaluation at 1M/10M
terms, fused ragged postings program vs the PR-0 scalar walk,
pair-median, correctness-gated at every device count).

Prints one JSON line per config (same shape as bench.py). Sizes are
env-tunable; defaults are sized to finish on CPU in a few minutes —
on a real TPU set M3_BENCH_SCALE=1 for the full north-star shapes.

    python -m m3_tpu.tools.bench_all [--configs 1,2,3,4,5] [--record FILE]

Methodology (the config-#1 approach throughout): the VALUE is the
framework's best serving path on the platform that exists — the XLA device
kernels when an accelerator is live, the native C++ batch/columnar kernels
(the real CPU dispatch targets per utils/dispatch + ops wiring) otherwise.
The BASELINE is a measured stand-in for the reference's hand-optimized Go
hot loop running the same workload:
  #1  frozen v1 single-core scalar C++ codec (byte-at-a-time bit I/O
      structurally matching the reference Go ostream/istream)
  #2  per-sample string-keyed entry lookup + lock + accumulator update
      (native/hostops.cpp m3_agg_baseline_scalar — the reference
      aggregator's AddUntimed map.go/entry.go/counter.go hot-loop shape)
  #3  per-(series, step) window re-scan rate (m3_rate_baseline_scalar —
      the prometheus/reference temporal-engine iteration shape)
  #4  compiled-regex fullmatch scan over the term vocabulary
  #5  numpy partition + scatter-add (selection-based, no strawman)
Every config asserts the serving output equals the baseline output before
reporting, so the speedup is never bought with a different answer.

Self-defense: a dead axon TPU tunnel hangs JAX init, and the axon hook
captures its env at INTERPRETER startup — an in-process env scrub is too
late (verified: `import jax` hangs even after setting JAX_PLATFORMS=cpu).
So the parent never imports jax: it socket-probes the tunnel and, when
dead, RE-EXECS itself as a child with the scrubbed env (the bench.py
defense), making every jax.* call below tunnel-safe.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_CHILD_ENV = "M3_BENCH_ALL_CHILD"
_ACCEL = False  # set by main(); child processes are always CPU


def _scale() -> float:
    try:
        return float(os.environ.get("M3_BENCH_SCALE", "0.1"))
    except ValueError:
        return 0.1


_RECORD: list[dict] = []


def _emit(metric: str, dp_per_sec: float, baseline: float) -> None:
    line = {
        "metric": metric,
        "value": round(dp_per_sec / 1e6, 3),
        "unit": "M datapoints/sec",
        "vs_baseline": round(dp_per_sec / baseline, 3) if baseline else 0.0,
    }
    _RECORD.append(line)
    print(json.dumps(line), flush=True)


def _time(fn, iters=3):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def _block(out):
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass


def _accelerator() -> bool:
    return _ACCEL


def config1_codec_roundtrip():
    """100k-series M3TSZ round-trip on the serving path vs the frozen v1
    scalar C++ baseline (the Go-hot-loop stand-in) — same methodology as
    bench.py: XLA codec on an accelerator, native v2 batch codec on CPU."""
    from __graft_entry__ import _example_batch
    from m3_tpu.encoding.m3tsz import native
    from m3_tpu.utils.xtime import TimeUnit

    B = max(int(100_000 * _scale()), 1024)
    T = 120
    times, vbits, start, n_points = _example_batch(B=B, T=T)
    values = vbits.view(np.float64)

    if _accelerator():
        import jax.numpy as jnp

        from m3_tpu.encoding.m3tsz import tpu

        jt, jv = jnp.asarray(times), jnp.asarray(vbits)
        js, jn = jnp.asarray(start), jnp.asarray(n_points)
        cap = (64 + 80 * T + 11 + 63) // 64

        def run():
            blocks = tpu.encode_bits(jt, jv, js, jn, TimeUnit.SECOND, cap)
            dec = tpu.decode(blocks.words, TimeUnit.SECOND, max_points=T)
            return blocks.words, dec.times

        dt = _time(run)
        rate = B * T / dt
        path = "xla device"
    elif native.available():
        native.bench_roundtrip_batch(times, values, int(start[0]),
                                     TimeUnit.SECOND)  # warm
        rates = [native.bench_roundtrip_batch(times, values, int(start[0]),
                                              TimeUnit.SECOND)[0]
                 for _ in range(3)]
        rate = sum(rates) / len(rates)
        path = f"native batch, {native.default_threads()}t"
    else:
        _emit(f"#1 m3tsz roundtrip {B}x{T} (no serving codec)", 0.0, 10e6)
        return
    base = None
    if native.available():
        base = native.bench_roundtrip(
            times[:4000], values[:4000], int(start[0]), TimeUnit.SECOND)
    _emit(f"#1 m3tsz roundtrip {B}x{T} [{path}]", rate, base or 10e6)


def config2_rollup():
    """1M-series counter+gauge rollup 10s -> 1m: the flush reduction on the
    serving path (device kernel on an accelerator, native columnar kernel on
    CPU — what windowed_agg dispatch actually runs) vs the measured
    per-sample scalar baseline (string-keyed entry lookup + lock + update,
    the reference AddUntimed hot-loop shape)."""
    from m3_tpu.ops import native_hostops, windowed_agg

    n = max(int(6_000_000 * _scale()), 100_000)  # 1M series x 6 samples
    rng = np.random.default_rng(0)
    n_series = n // 6
    e = rng.integers(0, n_series, n)
    w = rng.integers(0, 6, n)
    v = rng.normal(100, 10, n)
    t = rng.integers(0, 10**9, n)

    if _accelerator():
        os.environ["M3_TPU_DEVICE_OPS"] = "1"
        path = "xla device"
    else:
        path = f"native columnar, {native_hostops.default_threads()}t" \
            if native_hostops.available() else "numpy host"

    def serving():
        return windowed_agg.aggregate_groups(e, w, v, times=t,
                                             need_sorted=False)[2]["sum"]

    try:
        dt_serve = _time(serving)
    finally:
        os.environ.pop("M3_TPU_DEVICE_OPS", None)

    if not native_hostops.available():
        _emit(f"#2 rollup {n} samples -> {n_series} series [{path}, "
              "no native baseline]", n / dt_serve, 10e6)
        return
    # baseline: the reference per-sample shape over the SAME samples, with
    # the UNRESOLVED string ids it would hash per add
    ids = [b"stats.counter.%07d+env=prod,host=h%04d,dc=dc1" % (x, x % 1024)
           for x in e]
    native_hostops.agg_baseline_scalar(ids[:1000], w[:1000], v[:1000])  # warm
    t0 = time.perf_counter()
    checksum, _ = native_hostops.agg_baseline_scalar(ids, w, v)
    dt_base = time.perf_counter() - t0
    # correctness: same total across both paths
    serve_sum = float(np.asarray(serving()).sum())
    ok = np.isclose(checksum, serve_sum, rtol=1e-8)
    _emit(f"#2 rollup {n} samples -> {n_series} series [{path}]"
          + ("" if ok else " (CORRECTNESS FAILED)"),
          n / dt_serve, n / dt_base)


def config3_promql_rate_sum(tmp=None):
    """PromQL rate() over a wide fetch: the serving path (device kernel on
    an accelerator, native columnar pointer-walk on CPU — what
    windows.extrapolated_rate dispatch actually runs) vs the measured
    per-(series, step) window-rescan scalar baseline."""
    from m3_tpu.ops import native_hostops
    from m3_tpu.query.windows import NS, RaggedSeries
    from m3_tpu.query import windows

    S = max(int(100_000 * _scale()), 4_000)
    T = 240  # 1h at 15s
    per = []
    rng = np.random.default_rng(1)
    base_t = np.arange(T, dtype=np.int64) * 15 * NS
    for s in range(S):
        v = rng.integers(1, 10, T).astype(np.float64).cumsum()
        per.append((base_t, v))
    raws = RaggedSeries.from_lists(per)
    eval_ts = np.arange(300, 3600, 60, dtype=np.int64) * NS
    n_dp = S * T

    if _accelerator():
        os.environ["M3_TPU_DEVICE_OPS"] = "1"
        path = "xla device"
    else:
        path = f"native columnar, {native_hostops.default_threads()}t" \
            if native_hostops.available() else "numpy host"

    def serving():
        return windows.extrapolated_rate(raws, eval_ts, 300 * NS, True, True)

    try:
        dt_serve = _time(serving)
        served = np.asarray(serving())
    finally:
        os.environ.pop("M3_TPU_DEVICE_OPS", None)

    if not native_hostops.available():
        _emit(f"#3 rate() {S} series x {T} pts [{path}, no native baseline]",
              n_dp / dt_serve, 10e6)
        return
    sub = max(1, S // 10)  # baseline on a slice, extrapolated (it's slow)
    sub_off = raws.offsets[:sub + 1]

    def base():
        return native_hostops.rate_baseline_scalar(
            raws.times, raws.values, sub_off, eval_ts, 300 * NS, True, True)

    base()  # warm
    t0 = time.perf_counter()
    based = base()
    dt_base = (time.perf_counter() - t0) * (S / sub)
    ok = np.allclose(served[:sub], based, rtol=1e-9, equal_nan=True)
    _emit(f"#3 rate() {S} series x {T} pts [{path}]"
          + ("" if ok else " (CORRECTNESS FAILED)"),
          n_dp / dt_serve, n_dp / dt_base)


def config4_regex_postings():
    """High-cardinality regex queries over packed postings vs naive scan."""
    import re

    from m3_tpu.index import packed
    from m3_tpu.index.segment import Document

    n = max(int(10_000_000 * _scale()), 200_000)
    docs = [Document(i, b"s%08d" % i, [(b"pod", b"pod-%08d" % i)])
            for i in range(n)]
    seg = packed.build(docs)
    pats = [rb"pod-0000\d\d\d\d", rb"pod-000[0-4]\d+", rb"pod-.*99",
            rb"pod-0(1|2)\d+", rb"pod-00001[0-9]{3}"]
    pats = (pats * 10)[:50]

    def run_packed():
        total = 0
        for p in pats:
            seg._regex_cache.clear()
            total += len(seg.postings_regexp(b"pod", re.compile(p)))
        return total

    t0 = time.perf_counter()
    run_packed()
    dt = time.perf_counter() - t0
    # naive baseline: per-term fullmatch of ONE pattern, extrapolated to 50
    terms = seg.terms(b"pod")[: min(n, 200_000)]
    rx = re.compile(pats[0])
    t0 = time.perf_counter()
    sum(1 for t in terms if rx.fullmatch(t))
    naive_per_query = (time.perf_counter() - t0) * (n / len(terms))
    _emit(f"#4 50 regex queries over {n}-term postings",
          50 * n / dt, 50 * n / (50 * naive_per_query))


def config5_sharded_quantile():
    """4-shard timer quantile rollup with explicit cross-shard psum.

    The device program is the flagship ICI pattern: shard_map over the
    mesh, per-shard selection-based quantile (top_k, NOT a full sort — a
    p99 over a T-point window needs only the top T-ceil(0.99 T) elements)
    + local segment sums, then one psum pair across the shard axis. The
    host baseline is the same computation in numpy (np.partition + add.at,
    also selection-based — no strawman)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import m3_tpu.ops  # noqa: F401  (x64)

    shard_map = jax.shard_map if hasattr(jax, "shard_map") else None
    if shard_map is None:  # older jax
        from jax.experimental.shard_map import shard_map

    n_dev = min(4, len(jax.devices()))
    devices = np.array(jax.devices()[:n_dev])
    # bench-only: one mesh per config run, compile paid before timing
    # m3lint: disable=jax-jit-per-call
    mesh = Mesh(devices, axis_names=("shard",))
    S = max(int(10_000_000 * _scale()) // 64, 4096)
    S -= S % n_dev
    T = 64
    G = 128
    rng = np.random.default_rng(2)
    vals = rng.gamma(2.0, 10.0, (S, T))
    gids = (np.arange(S) % G).astype(np.int32)
    q_idx = int(T * 0.99)
    k = T - q_idx  # selection depth: sorted[q_idx] == k-th largest

    def kth_largest_time_major(v, kk):
        # iterative masked-max selection over the TIME axis of a
        # time-major [T, S] elem grid: kk-1 passes peel the larger
        # elements, pass kk's max is the answer. O(kk*T) elementwise — no
        # sort, no top_k (XLA:CPU lowers top_k to a full variadic sort;
        # TPU tiles elementwise reductions onto the VPU directly). Each
        # pass's reduction is a vertical SIMD op across series lanes.
        for _ in range(kk - 1):
            m = jnp.max(v, axis=0, keepdims=True)
            # mask exactly one occurrence of the max per series
            first = jnp.cumsum(v == m, axis=0) == 1
            v = jnp.where(first & (v == m), -jnp.inf, v)
        return jnp.max(v, axis=0)

    # group counts AND the group->series one-hot placement matrix depend
    # only on the shard->group placement, not on the flushed values:
    # precompute both once (the host baseline likewise only does the
    # per-flush work — partition + scatter-add — in its timed section)
    cnt_host = np.bincount(gids, minlength=G).astype(np.float64)
    onehot_t_host = np.zeros((G, S))
    onehot_t_host[gids, np.arange(S)] = 1.0

    # the segment reduction is a one-hot MATVEC, not segment_sum:
    # XLA:CPU lowers segment_sum to a serial scatter-add, while
    # [G, S_shard] @ [S_shard] runs through the tuned GEMV (a TPU tiles
    # it onto the MXU). Orientation matters: the GROUP-major [G, S]
    # one-hot makes every output group one contiguous SIMD dot; the
    # [S, G] orientation (q @ oh) pays a stride-G gather per group —
    # profiled ~2.6x between them, ~4x over segment_sum

    def per_shard_select(v, oht, cnt):  # time-major [T, S_shard]
        seg = oht @ kth_largest_time_major(v, k)
        return jax.lax.psum(seg, "shard") / cnt

    def per_shard_max(v, oht, cnt):  # series-major [S_shard, T]
        seg = oht @ jnp.max(v, axis=1)
        return jax.lax.psum(seg, "shard") / cnt

    # layout is ours to choose for device-resident state, PER selection
    # depth: k == 1 (p99 over a 64-pt window) degenerates to a plain max,
    # which the series-major [S, T] grid serves with one contiguous
    # horizontal reduce per row — profiled ~1.9x over running the k=1
    # peel on the time-major grid. Deeper selections keep the time-major
    # grid the iterative peel prefers. The host baseline keeps its own
    # best layout (row-major [S, T] for np.partition) either way.
    if k == 1:
        fn, spec, dev_vals = per_shard_max, P("shard", None), vals
    else:
        fn, spec, dev_vals = per_shard_select, P(None, "shard"), vals.T.copy()
    # bench-only: built once per config run, and the warmup call below
    # pays the compile before the timed region starts
    # m3lint: disable=jax-jit-per-call
    quantile_rollup = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(spec, P(None, "shard"), P()), out_specs=P(),
    ))

    # bench-only, once per config run (not per eval)
    # m3lint: disable=jax-jit-per-call
    sh_v, sh_oh, sh_c = (jax.NamedSharding(mesh, s)
                         for s in (spec, P(None, "shard"), P()))
    jv = jax.device_put(jnp.asarray(dev_vals), sh_v)
    joh = jax.device_put(jnp.asarray(onehot_t_host), sh_oh)
    jc = jax.device_put(jnp.asarray(np.maximum(cnt_host, 1.0)), sh_c)
    # both sides run the same iteration count, high enough to average
    # out scheduler noise (at 3 iters the run-to-run spread exceeded the
    # device/host gap on shared-CPU hosts)
    iters = 15
    # bench-only: the timed region measures raw kernel dispatch — a
    # tracker would add exactly the overhead config #16 bounds
    # m3lint: disable=inv-jit-tracked
    dt = _time(lambda: quantile_rollup(jv, joh, jc), iters=iters)

    # host numpy baseline of the same computation
    def host():
        q = np.partition(vals, q_idx, axis=1)[:, q_idx]
        out = np.zeros(G)
        np.add.at(out, gids, q)
        return out

    t0 = time.perf_counter()
    for _ in range(iters):
        host()
    dt_host = (time.perf_counter() - t0) / iters
    # correctness: device result == host result (bench-only, same raw
    # dispatch as the timed region)
    # m3lint: disable=inv-jit-tracked
    dev = np.asarray(quantile_rollup(jv, joh, jc))
    ok = np.allclose(dev, host() / np.maximum(cnt_host, 1), rtol=1e-9)
    _emit(f"#5 {n_dev}-shard timer quantile rollup {S}x{T}"
          + ("" if ok else " (CORRECTNESS FAILED)"),
          S * T / dt, S * T / dt_host)


def config6_read_many():
    """Batched multi-series fetch (config #3's fetch leg, measured
    directly): Namespace.read_many — grouping by (shard, block, volume)
    with ONE fused fetch+decode dispatch per group — vs the per-series
    read loop it replaced (one Python round-trip + cache probe + decode
    dispatch per series). Both single-threaded, cold cache, so the ratio
    is pure dispatch economy, not parallelism."""
    import tempfile

    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.fileset import FilesetWriter
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.xtime import TimeUnit

    NS = 10**9
    BLOCK = 3600 * NS
    START = 1_600_000_000 * NS
    T = 24
    n_blocks, n_shards = 2, 8
    prev_threads = os.environ.get("M3_NATIVE_THREADS")
    os.environ["M3_NATIVE_THREADS"] = "1"
    try:
        for B in (10_000, 100_000):
            with tempfile.TemporaryDirectory() as root:
                db = Database(root, DatabaseOptions(
                    n_shards=n_shards, block_cache_entries=0))  # cold cache
                ns = db.create_namespace("default", NamespaceOptions(
                    retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                               block_size_ns=BLOCK),
                    index=IndexOptions(enabled=False),
                    writes_to_commitlog=False, snapshot_enabled=False))
                ids = [b"series-%07d" % i for i in range(B)]
                by_shard: dict[int, list[bytes]] = {}
                for sid in ids:
                    by_shard.setdefault(ns.shard_set.lookup(sid), []).append(sid)
                rng = np.random.default_rng(0)
                for shard_id, sids in by_shard.items():
                    for b in range(n_blocks):
                        bs = START + b * BLOCK
                        nb = len(sids)
                        times = np.broadcast_to(
                            bs + np.arange(T, dtype=np.int64) * 10 * NS,
                            (nb, T)).copy()
                        vbits = rng.normal(100.0, 20.0, (nb, T)) \
                            .view(np.uint64)
                        streams = hostpath.encode_blocks(
                            times, vbits, np.full(nb, bs, np.int64),
                            np.full(nb, T, np.int32), TimeUnit.SECOND, False)
                        w = FilesetWriter(db.fs_root, "default", shard_id,
                                          bs, BLOCK, 0)
                        for sid, stream in zip(sids, streams):
                            w.write_series(sid, b"", stream)
                        w.close()
                db.open(START + n_blocks * BLOCK)
                t_lo, t_hi = START, START + n_blocks * BLOCK
                n_dp = B * T * n_blocks

                batched = ns.read_many(ids, t_lo, t_hi)  # warm code paths
                t0 = time.perf_counter()
                batched = ns.read_many(ids, t_lo, t_hi)
                dt_batch = time.perf_counter() - t0

                t0 = time.perf_counter()
                scalar = [ns.read(sid, t_lo, t_hi) for sid in ids]
                dt_loop = time.perf_counter() - t0
                ok = all(np.array_equal(bt, st) and np.array_equal(bv, sv)
                         for (bt, bv), (st, sv)
                         in zip(batched[::max(1, B // 200)],
                                scalar[::max(1, B // 200)]))
                db.close()
            _emit(f"#6 read_many {B} series x {T * n_blocks} pts cold "
                  "fetch+decode [batched per (shard, block), 1t]"
                  + ("" if ok else " (CORRECTNESS FAILED)"),
                  n_dp / dt_batch, n_dp / dt_loop)
    finally:
        if prev_threads is None:
            os.environ.pop("M3_NATIVE_THREADS", None)
        else:
            os.environ["M3_NATIVE_THREADS"] = prev_threads


def config7_tracing_overhead():
    """Observability-overhead guard on the write hot path (PR-4, widened
    in PR-6): the SHIPPED path (tracer enabled at sample_every=1,
    per-write latency histogram WITH exemplar capture, and a live
    telemetry-exporter drainer shipping the registry+span ring to a file
    sink every 0.5s) vs the seed-equivalent path (tracer disabled,
    histogram observe no-oped, no exporter). The disabled-path cost must
    stay within noise of seed: vs_baseline is shipped/seed throughput and
    the run flags anything below 0.85 (beyond run-to-run noise on shared
    hosts)."""
    import tempfile

    from m3_tpu.storage import database as database_mod
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils import trace

    NS = 10**9
    START = 1_600_000_000 * NS
    N = max(int(400_000 * _scale()), 40_000)

    # pure CPU write path (no commitlog/index I/O): filesystem jitter on
    # shared hosts would otherwise swamp the effect being guarded
    def run_once() -> float:
        with tempfile.TemporaryDirectory() as root:
            db = Database(root, DatabaseOptions(n_shards=4))
            db.create_namespace("default", NamespaceOptions(
                retention=RetentionOptions(retention_ns=1000 * 3600 * NS,
                                           block_size_ns=3600 * NS),
                index=IndexOptions(enabled=False),
                writes_to_commitlog=False, snapshot_enabled=False))
            db.open(START)
            names = [b"m%05d" % i for i in range(1000)]
            tags = [(b"k", b"v")]
            t0 = time.perf_counter()
            for i in range(N):
                db.write_tagged("default", names[i % 1000], tags,
                                START + (i % 3600) * NS, float(i))
            dt = time.perf_counter() - t0
            db.close()
        return N / dt

    tracer = trace.default_tracer()
    real_observe_write = database_mod._observe_write

    def seed_equivalent(on: bool):
        tracer.enabled = on
        database_mod._observe_write = real_observe_write if on \
            else (lambda v: None)

    # paired interleaved runs, median of the per-pair ratios: host drift
    # on shared CPUs exceeds the effect size, and back-to-back pairing +
    # median is the standard way to cancel it. The shipped side runs
    # under a LIVE exporter drainer (file sink, 0.5s interval) so the
    # guard covers the full PR-6 observability stack.
    from m3_tpu.utils.export import FileSink, TelemetryExporter

    ratios: list[float] = []
    rate_on = rate_off = 0.0
    try:
        seed_equivalent(True)
        run_once()  # warm the code paths once, outside any pair
        for _ in range(5):
            seed_equivalent(True)
            with tempfile.TemporaryDirectory() as sink_dir:
                exporter = TelemetryExporter(
                    "bench", FileSink(f"{sink_dir}/telemetry.jsonl"),
                    interval_s=0.5)
                exporter.start()
                try:
                    on = run_once()
                finally:
                    exporter.close()
            seed_equivalent(False)
            off = run_once()
            ratios.append(on / off)
            rate_on, rate_off = max(rate_on, on), max(rate_off, off)
    finally:
        seed_equivalent(True)
    ratios.sort()
    ratio = ratios[len(ratios) // 2]
    _emit("#7 write hot path w/ observability vs seed-equivalent"
          + ("" if ratio >= 0.85 else " (OVERHEAD EXCEEDED)"),
          ratio * rate_off, rate_off)


def config8_write_batch():
    """Batched ingest (the write-side twin of #6): Database.write_batch —
    one columnar pass per (namespace, shard): memoized series identity,
    vectorized murmur3 shard routing, ONE commitlog append per batch,
    one buffer lock per (shard, window) group, pre-filtered index
    inserts — vs the per-entry write_tagged loop it replaces. Both
    single-threaded with commitlog + index ON (the real ingest path).
    Correctness: both databases must read back identically and their
    commitlogs must replay the same entry stream."""
    import tempfile

    from m3_tpu.storage import commitlog
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.ident import tags_to_id

    NS = 10**9
    START = 1_600_000_000 * NS

    def new_db(root):
        db = Database(root, DatabaseOptions(n_shards=8))
        db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=1000 * 3600 * NS,
                                       block_size_ns=3600 * NS),
            index=IndexOptions(enabled=True, block_size_ns=3600 * NS),
            writes_to_commitlog=True, snapshot_enabled=False))
        db.open(START)
        return db

    names = [b"m%05d" % i for i in range(1000)]
    for B in (10_000, 100_000):
        # ~2000 distinct series, 2 block windows: a realistic ingest mix
        # of repeated identities across shards
        entries = [
            (names[i % 1000], [(b"host", b"h%03d" % (i % 100))],
             START + (i % 7200) * NS, float(i))
            for i in range(B)
        ]
        with tempfile.TemporaryDirectory() as r1, \
                tempfile.TemporaryDirectory() as r2, \
                tempfile.TemporaryDirectory() as rw:
            warm = new_db(rw)  # warm both code paths off the timed dbs
            warm.write_batch("default", entries[:256])
            for m, tg, t, v in entries[:256]:
                warm.write_tagged("default", m, tg, t, v)
            warm.close()

            db_b = new_db(r1)
            t0 = time.perf_counter()
            results = db_b.write_batch("default", entries)
            dt_batch = time.perf_counter() - t0
            ok = all(r is None for r in results)

            db_l = new_db(r2)
            t0 = time.perf_counter()
            for m, tg, t, v in entries:
                db_l.write_tagged("default", m, tg, t, v)
            dt_loop = time.perf_counter() - t0

            # parity: sampled series read identically, and both WALs
            # replay the same entry stream
            sample = {tags_to_id(m, tg) for m, tg, _t, _v in entries[::503]}
            for sid in sample:
                bt, bv = db_b.namespaces["default"].read(
                    sid, START, START + 7200 * NS)
                lt, lv = db_l.namespaces["default"].read(
                    sid, START, START + 7200 * NS)
                ok = ok and np.array_equal(bt, lt) and np.array_equal(bv, lv)
            db_b._commitlogs["default"].flush(fsync=True)
            db_l._commitlogs["default"].flush(fsync=True)
            eb = commitlog.replay(
                commitlog.log_files(db_b.commitlog_dir("default"))[0])
            el = commitlog.replay(
                commitlog.log_files(db_l.commitlog_dir("default"))[0])
            ok = ok and [(e.series_id, e.time_ns, e.value_bits) for e in eb] \
                == [(e.series_id, e.time_ns, e.value_bits) for e in el]
            db_b.close()
            db_l.close()
        _emit(f"#8 write_batch {B} entries commitlog+index "
              "[columnar per (shard, window), 1t]"
              + ("" if ok else " (CORRECTNESS FAILED)"),
              B / dt_batch, B / dt_loop)


def config9_query_compile():
    """End-to-end query_range latency, whole-query-compiled vs op-by-op
    interpreted (ROADMAP #2 — the number a p99 user actually sees, not
    per-op throughput): one coordinator-shaped Engine over a real
    fileset+index namespace, 10k series x 48h of samples, a 2m-step
    dashboard grid (~1.4k steps). Paired INTERLEAVED runs with the
    median of per-pair ratios (this host is +-30% noisy; single shots
    are meaningless). Both sides share fetch/decode/limits — the ratio
    isolates exactly what compilation changes. Correctness gate: the
    compiled result must match the interpreter element-identically
    (NaN-mask equal, values within 1e-9 relative — the documented XLA
    reassociation envelope) before anything is reported.

    Shapes: the instant-delta dashboard (`max by (host) (irate(...))`,
    no native interpreter kernel — the fused program's win) and the
    windowed-aggregation dashboard (`avg by (host) (avg_over_time(...))`).
    Extrapolated-rate plans are deliberately absent: on a CPU-only
    backend the per-plan dispatch policy hands those to the
    interpreter's native rate_csr kernel (compiler._host_prefers_
    interpreter), which profiled ~2.4x faster than the XLA lowering."""
    import tempfile

    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.query.engine import Engine
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.fileset import FilesetWriter
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.xtime import TimeUnit

    NS = 10**9
    BLOCK = 48 * 3600 * NS
    START = 1_600_000_000 * NS
    S = 10_000
    SAMP = 300 * NS                # one sample per 5m per series
    T = (48 * 3600 * NS) // SAMP   # 576 samples per series
    with tempfile.TemporaryDirectory() as root:
        db = Database(root, DatabaseOptions(
            n_shards=8, block_cache_entries=100_000))  # warm-cache serving
        ns = db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                       block_size_ns=BLOCK),
            index=IndexOptions(enabled=True, block_size_ns=BLOCK),
            writes_to_commitlog=False, snapshot_enabled=False))
        ids = [b"reqs,host=h%04d,i=%05d" % (i % 200, i) for i in range(S)]
        fields = [[(b"__name__", b"reqs"), (b"host", b"h%04d" % (i % 200)),
                   (b"i", b"%05d" % i)] for i in range(S)]
        by_shard: dict[int, list[int]] = {}
        for j, sid in enumerate(ids):
            by_shard.setdefault(ns.shard_set.lookup(sid), []).append(j)
        rng = np.random.default_rng(0)
        for shard_id, rows in by_shard.items():
            nb = len(rows)
            times = np.broadcast_to(
                START + np.arange(T, dtype=np.int64) * SAMP, (nb, T)).copy()
            vals = rng.integers(1, 10, (nb, T)).astype(np.float64) \
                .cumsum(axis=1)
            streams = hostpath.encode_blocks(
                times, vals.view(np.uint64), np.full(nb, START, np.int64),
                np.full(nb, T, np.int32), TimeUnit.SECOND, False)
            w = FilesetWriter(db.fs_root, "default", shard_id, START,
                              BLOCK, 0)
            for j, stream in zip(rows, streams):
                w.write_series(ids[j], b"", stream)
            w.close()
        db.open(START + BLOCK)
        ns.index.insert_many(ids, fields, np.full(S, START, np.int64))
        eng = Engine(db, resolve_tiers=False)
        qstart = START + 30 * 60 * NS
        qend = START + 48 * 3600 * NS - SAMP
        step = 2 * 60 * NS
        n_dp = S * T  # samples the query reads end to end

        prev = os.environ.get("M3_TPU_QUERY_COMPILE")
        try:
            for label, q in (
                ("irate max-by", "max by (host) (irate(reqs[30m]))"),
                ("avg_over_time avg-by",
                 "avg by (host) (avg_over_time(reqs[30m]))"),
            ):
                def run():
                    return eng.query_range(q, qstart, qend, step)[0]

                os.environ["M3_TPU_QUERY_COMPILE"] = "1"
                v_c = run()  # warm: pays the one plan compile
                os.environ["M3_TPU_QUERY_COMPILE"] = "0"
                v_i = run()
                ok = (v_c.labels == v_i.labels
                      and np.array_equal(np.isnan(v_c.values),
                                         np.isnan(v_i.values))
                      and np.allclose(v_c.values, v_i.values, rtol=1e-9,
                                      atol=0, equal_nan=True))
                pairs: list[tuple[float, float, float]] = []
                for _ in range(5):
                    os.environ["M3_TPU_QUERY_COMPILE"] = "1"
                    t0 = time.perf_counter()
                    run()
                    dt_c = time.perf_counter() - t0
                    os.environ["M3_TPU_QUERY_COMPILE"] = "0"
                    t0 = time.perf_counter()
                    run()
                    dt_i = time.perf_counter() - t0
                    pairs.append((dt_i / dt_c, n_dp / dt_c, n_dp / dt_i))
                # report the MEDIAN pair's measured numbers: value is a
                # real compiled-side throughput and vs_baseline is the
                # pair-median ratio, not a synthetic best-x-median blend
                pairs.sort(key=lambda p: p[0])
                _ratio, thr_c, thr_i = pairs[len(pairs) // 2]
                _emit(f"#9 query_range e2e {S} series x ~1.4k steps "
                      f"[{label}, compiled vs interpreted]"
                      + ("" if ok else " (CORRECTNESS FAILED)"),
                      thr_c, thr_i)
        finally:
            if prev is None:
                os.environ.pop("M3_TPU_QUERY_COMPILE", None)
            else:
                os.environ["M3_TPU_QUERY_COMPILE"] = prev


def config10_profiler_overhead():
    """Profiler-overhead guard (the PR-11 twin of #7): the write hot
    path with the WHOLE profiling & saturation plane armed — sampling
    profiler at ~19 Hz, lock-wait profiling wrapping every
    threading.Lock/RLock the timed code creates, stall-watchdog checker
    running — vs the same path with all of it off. Same pairing
    discipline as #7 (interleaved pairs, median ratio, 0.85 noise bar):
    'always-on profiling' is only true if this number stays at 1.0-ish."""
    import tempfile

    from m3_tpu.storage.database import Database
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils import profiler

    NS = 10**9
    START = 1_600_000_000 * NS
    N = max(int(400_000 * _scale()), 40_000)

    # pure CPU write path (no commitlog/index I/O), as in #7: the effect
    # being guarded is per-write lock/sampling overhead, not disk jitter
    def run_once() -> float:
        with tempfile.TemporaryDirectory() as root:
            db = Database(root, DatabaseOptions(n_shards=4))
            db.create_namespace("default", NamespaceOptions(
                retention=RetentionOptions(retention_ns=1000 * 3600 * NS,
                                           block_size_ns=3600 * NS),
                index=IndexOptions(enabled=False),
                writes_to_commitlog=False, snapshot_enabled=False))
            db.open(START)
            names = [b"m%05d" % i for i in range(1000)]
            tags = [(b"k", b"v")]
            t0 = time.perf_counter()
            for i in range(N):
                db.write_tagged("default", names[i % 1000], tags,
                                START + (i % 3600) * NS, float(i))
            dt = time.perf_counter() - t0
            db.close()
        return N / dt

    prof = profiler.default_profiler()
    wd = profiler.default_watchdog()

    def armed(on: bool):
        # the timed Database is constructed AFTER the factory swap, so
        # the armed side's storage locks are all profiled wrappers
        if on:
            profiler.install_lock_profiling()
            prof.start(profiler.DEFAULT_HZ)
            wd.start()
        else:
            prof.stop()
            wd.stop()
            profiler.uninstall_lock_profiling()

    ratios: list[float] = []
    rate_off = 0.0
    try:
        armed(True)
        run_once()  # warm code paths once, outside any pair
        for _ in range(5):
            armed(True)
            on_rate = run_once()
            armed(False)
            off_rate = run_once()
            ratios.append(on_rate / off_rate)
            rate_off = max(rate_off, off_rate)
    finally:
        armed(False)
        profiler.reset_lock_stats()
    ratios.sort()
    ratio = ratios[len(ratios) // 2]
    _emit("#10 write hot path w/ profiler+locks+watchdog armed vs off"
          + ("" if ratio >= 0.85 else " (OVERHEAD EXCEEDED)"),
          ratio * rate_off, rate_off)


def config11_sharded_query():
    """Sharded multi-device query plane (PR 12, ROADMAP #1): end-to-end
    query_range + grouped aggregation with the SAME fused program on the
    series-sharded mesh vs single-device, swept over device counts on
    the virtual CPU mesh (the shape that becomes a multi-chip bench the
    day the TPU tunnel returns). Both sides run whole-query-compiled
    (M3_TPU_QUERY_COMPILE=1), so the ratio isolates exactly what the
    mesh changes: per-device sample slabs (device-local gathers), SPMD
    stage partitioning, psum-lowered grouped reductions. Pairing
    discipline as #9 (interleaved pairs, median-pair numbers; this host
    is +-30% noisy). Correctness gate: the sharded result must match the
    interpreter element-identically (NaN masks exact, values within the
    documented 1e-9 reassociation envelope) before anything is
    reported."""
    import tempfile

    import jax

    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.query.engine import Engine
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.fileset import FilesetWriter
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.xtime import TimeUnit

    NS = 10**9
    BLOCK = 48 * 3600 * NS
    START = 1_600_000_000 * NS
    S = 4096
    SAMP = 300 * NS                # one sample per 5m per series
    T = (48 * 3600 * NS) // SAMP   # 576 samples per series
    n_devices = len(jax.devices())
    if n_devices < 2:
        # a live single-device accelerator runs in-process (no virtual
        # CPU re-exec): nothing to shard — note it, record nothing
        print(json.dumps({"metric": "#11 sharded query skipped: 1 device",
                          "value": 0.0, "unit": "M datapoints/sec",
                          "vs_baseline": 0.0}), flush=True)
        return
    with tempfile.TemporaryDirectory() as root:
        db = Database(root, DatabaseOptions(
            n_shards=8, block_cache_entries=100_000))  # warm-cache serving
        ns = db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                       block_size_ns=BLOCK),
            index=IndexOptions(enabled=True, block_size_ns=BLOCK),
            writes_to_commitlog=False, snapshot_enabled=False))
        ids = [b"reqs,host=h%04d,i=%05d" % (i % 128, i) for i in range(S)]
        fields = [[(b"__name__", b"reqs"), (b"host", b"h%04d" % (i % 128)),
                   (b"i", b"%05d" % i)] for i in range(S)]
        by_shard: dict[int, list[int]] = {}
        for j, sid in enumerate(ids):
            by_shard.setdefault(ns.shard_set.lookup(sid), []).append(j)
        rng = np.random.default_rng(0)
        for shard_id, rows in by_shard.items():
            nb = len(rows)
            times = np.broadcast_to(
                START + np.arange(T, dtype=np.int64) * SAMP, (nb, T)).copy()
            vals = rng.integers(1, 10, (nb, T)).astype(np.float64) \
                .cumsum(axis=1)
            streams = hostpath.encode_blocks(
                times, vals.view(np.uint64), np.full(nb, START, np.int64),
                np.full(nb, T, np.int32), TimeUnit.SECOND, False)
            w = FilesetWriter(db.fs_root, "default", shard_id, START,
                              BLOCK, 0)
            for j, stream in zip(rows, streams):
                w.write_series(ids[j], b"", stream)
            w.close()
        db.open(START + BLOCK)
        ns.index.insert_many(ids, fields, np.full(S, START, np.int64))
        eng = Engine(db, resolve_tiers=False)
        qstart = START + 30 * 60 * NS
        qend = START + 48 * 3600 * NS - SAMP
        step = 2 * 60 * NS
        n_dp = S * T
        q = "sum by (host) (rate(reqs[30m]))"

        prev = {k: os.environ.get(k)
                for k in ("M3_TPU_QUERY_COMPILE", "M3_TPU_QUERY_SHARD")}
        try:
            os.environ["M3_TPU_QUERY_COMPILE"] = "1"

            def run(shard: int):
                os.environ["M3_TPU_QUERY_SHARD"] = str(shard)
                return eng.query_range(q, qstart, qend, step)[0]

            # correctness gate: sharded fused result vs the interpreter
            v_s = run(n_devices)
            os.environ["M3_TPU_QUERY_COMPILE"] = "0"
            v_i = eng.query_range(q, qstart, qend, step)[0]
            os.environ["M3_TPU_QUERY_COMPILE"] = "1"
            ok = (v_s.labels == v_i.labels
                  and np.array_equal(np.isnan(v_s.values),
                                     np.isnan(v_i.values))
                  and np.allclose(v_s.values, v_i.values, rtol=1e-9,
                                  atol=0, equal_nan=True))
            run(0)  # warm the single-device executable too
            sweep_ratios: list[str] = []
            headline = None
            for n_dev in [n for n in (2, 4, 8) if n <= n_devices]:
                run(n_dev)  # pay this mesh's compile outside the pairs
                pairs: list[tuple[float, float, float]] = []
                for _ in range(9):
                    t0 = time.perf_counter()
                    run(n_dev)
                    dt_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    run(0)
                    dt_1 = time.perf_counter() - t0
                    pairs.append((dt_1 / dt_s, n_dp / dt_s, n_dp / dt_1))
                pairs.sort(key=lambda p: p[0])
                med = pairs[len(pairs) // 2]
                sweep_ratios.append(f"{n_dev}dev:{med[0]:.2f}x")
                headline = med  # the widest mesh is the recorded headline
            _ratio, thr_s, thr_1 = headline
            _emit(f"#11 sharded query_range e2e {S} series x ~1.4k steps "
                  f"[sum-by(rate), {n_devices}-device series mesh vs "
                  f"single-device; sweep {' '.join(sweep_ratios)}]"
                  + ("" if ok else " (CORRECTNESS FAILED)"),
                  thr_s, thr_1)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        db.close()


def _sparse_multigroup_setup(root, S, NB, T):
    """The #12 workload: a SPARSE high-cardinality multi-group namespace
    — S series x NB block volumes, a handful of points per (series,
    block) — plus the query that scans it end to end.  Shared by #12
    (pipelined vs serial) and #13 (paged ragged finalize vs the seed
    per-series concatenate path)."""
    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.query.engine import Engine
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.fileset import FilesetWriter
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.xtime import TimeUnit

    NS = 10**9
    BLOCK = 3600 * NS
    START = 1_600_000_000 * NS
    db = Database(root, DatabaseOptions(
        n_shards=8, block_cache_entries=0))  # cold multi-group scans
    ns = db.create_namespace("default", NamespaceOptions(
        retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                   block_size_ns=BLOCK),
        index=IndexOptions(enabled=True, block_size_ns=BLOCK),
        writes_to_commitlog=False, snapshot_enabled=False))
    ids = [b"reqs,host=h%04d,i=%05d" % (i % 100, i) for i in range(S)]
    fields = [[(b"__name__", b"reqs"), (b"host", b"h%04d" % (i % 100)),
               (b"i", b"%05d" % i)] for i in range(S)]
    by_shard: dict[int, list[int]] = {}
    for j, sid in enumerate(ids):
        by_shard.setdefault(ns.shard_set.lookup(sid), []).append(j)
    rng = np.random.default_rng(0)
    for b in range(NB):
        bs = START + b * BLOCK
        for shard_id, rows in by_shard.items():
            nb = len(rows)
            times = np.broadcast_to(
                bs + np.arange(T, dtype=np.int64) * (BLOCK // T),
                (nb, T)).copy()
            vals = rng.integers(1, 10, (nb, T)).astype(np.float64) \
                .cumsum(axis=1)
            streams = hostpath.encode_blocks(
                times, vals.view(np.uint64), np.full(nb, bs, np.int64),
                np.full(nb, T, np.int32), TimeUnit.SECOND, False)
            w = FilesetWriter(db.fs_root, "default", shard_id, bs,
                              BLOCK, 0)
            for j, stream in zip(rows, streams):
                w.write_series(ids[j], b"", stream)
            w.close()
    db.open(START + NB * BLOCK)
    ns.index.insert_many(ids, fields, np.full(S, START, np.int64))
    eng = Engine(db, resolve_tiers=False)
    q = "sum by (host) (sum_over_time(reqs[30m]))"
    qs = START + 30 * 60 * NS
    qe = START + NB * BLOCK - 60 * NS
    step = 30 * 60 * NS

    def run():
        return eng.query_range(q, qs, qe, step)[0]

    return run


def config12_pipelined_read():
    """Pipelined dataflow (ISSUE 14 / ROADMAP #2): end-to-end
    query_range over the sparse multi-group workload
    (_sparse_multigroup_setup) — the shape where the per-(shard, block)
    gather rung dominates the fetch (ROADMAP #3's sparse-series
    premise). Pipelined (M3_TPU_PIPELINE=1: per-group gathers prefetched
    on the executor behind the decode rung, columnar row-index gather,
    cache bookkeeping skipped while the block cache is disabled — this
    is a cold scan) vs the pinned serial seed path (=0: per-query
    merge-join walk, inline legs). Same pairing discipline as #9:
    interleaved pairs, MEDIAN pair reported, correctness gated on exact
    NaN masks + 1e-9 values BEFORE anything is emitted. On a multi-core
    host the executor adds genuine gather/decode wall-clock overlap on
    top of the columnar gather; this 1-core container measures the
    restructured dataflow alone."""
    import tempfile

    NS = 10**9
    BLOCK = 3600 * NS
    S = max(int(160_000 * _scale()), 2048)
    NB, T = 12, 4
    with tempfile.TemporaryDirectory() as root:
        run = _sparse_multigroup_setup(root, S, NB, T)
        n_dp = S * NB * T  # samples the query reads end to end

        prev = os.environ.get("M3_TPU_PIPELINE")
        try:
            os.environ["M3_TPU_PIPELINE"] = "1"
            v_p = run()
            os.environ["M3_TPU_PIPELINE"] = "0"
            v_s = run()
            ok = (v_p.labels == v_s.labels
                  and np.array_equal(np.isnan(v_p.values),
                                     np.isnan(v_s.values))
                  and np.allclose(v_p.values, v_s.values, rtol=1e-9,
                                  atol=0, equal_nan=True))
            pairs: list[tuple[float, float, float]] = []
            for _ in range(7):
                os.environ["M3_TPU_PIPELINE"] = "1"
                t0 = time.perf_counter()
                run()
                dt_p = time.perf_counter() - t0
                os.environ["M3_TPU_PIPELINE"] = "0"
                t0 = time.perf_counter()
                run()
                dt_s = time.perf_counter() - t0
                pairs.append((dt_s / dt_p, n_dp / dt_p, n_dp / dt_s))
            pairs.sort(key=lambda p: p[0])
            _ratio, thr_p, thr_s = pairs[len(pairs) // 2]
            _emit(f"#12 pipelined read_many->query e2e {S} series x "
                  f"{NB} blocks [sparse multi-group scan, pipelined vs "
                  f"serial]" + ("" if ok else " (CORRECTNESS FAILED)"),
                  thr_p, thr_s)
        finally:
            if prev is None:
                os.environ.pop("M3_TPU_PIPELINE", None)
            else:
                os.environ["M3_TPU_PIPELINE"] = prev


def config13_paged_memory():
    """Paged ragged columnar memory (ISSUE 15 / ROADMAP #3), two legs.

    (a) Write+read STEADY STATE at 1M live series (the default-scale
    lane runs the honest million): bulk write_many rounds into the
    page-pool buffer, one warm flush (ragged seal + length-bucketed
    encode), more live rounds, then a batched read merging fileset +
    live buffer — M3_TPU_PAGED=1 vs the pinned seed grow-array/
    per-series-concatenate path (=0), interleaved pairs, MEDIAN pair
    reported with RSS and p99 ingest-round wall time in the metric
    line.  The baseline's read rate is measured on a 1/64 series
    subset (its per-series cost is constant in subset size — the full
    quadratic scan takes hours, which is the point of this PR) and
    charged at that rate for the full read volume.

    (b) The #12 sparse multi-group e2e query shape with the PIPELINE
    armed on BOTH sides, toggling only M3_TPU_PAGED — isolating the
    ragged finalize (finish_read's per-series np.concatenate +
    merge_dedup tax, profiled ~15% of this path in PR 14) from the
    overlap win #12 already records. Correctness gated on exact NaN
    masks + 1e-9 values before anything is emitted."""
    import tempfile

    from m3_tpu.storage.database import Database
    from m3_tpu.storage.options import (
        DatabaseOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils.selfscrape import rss_bytes

    NS = 10**9
    BLOCK = 3600 * NS
    START = 1_600_000_000 * NS - (1_600_000_000 * NS) % (3600 * NS)
    # 1M live series AT THE DEFAULT 0.1 SCALE — the ROADMAP #3 acceptance
    # bench is the honest million, not a scaled stand-in
    S = max(int(10_000_000 * _scale()), 8192)
    ROUNDS = 2  # write rounds per block window

    def steady_state(root, paged: str):
        """One full side: write ROUNDS rounds into two block windows
        (flushing the first — live buffer + fileset merge on the read),
        then a batched read.  The PAGED side reads every live series;
        the grow-array baseline reads a 1/64 SUBSET — its per-series
        finalize cost is CONSTANT in subset size (each buffer.read masks
        the whole window log regardless), so the subset's datapoints/sec
        is the baseline's exact full-read rate, measured in minutes
        instead of the hours the quadratic full scan actually takes at
        1M live series.  Throughput combines the measured write wall
        with the full read volume at the measured read rate."""
        os.environ["M3_TPU_PAGED"] = paged
        db = Database(root, DatabaseOptions(n_shards=4,
                                            block_cache_entries=0))
        ns = db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                       block_size_ns=BLOCK),
            writes_to_commitlog=False, snapshot_enabled=False))
        db.open(START)
        ids = [b"m%07d" % i for i in range(S)]
        tags = [b""] * S
        lat = []
        write_dp = 0
        t_write0 = time.perf_counter()
        for b in range(2):
            bs = START + b * BLOCK
            for r in range(ROUNDS):
                times = np.full(S, bs + (r + 1) * 60 * NS, np.int64)
                # per-series distinct values: the correctness digest
                # below sums them, so a read path that scrambles or
                # zeroes values across series cannot slip through
                vals = (np.arange(S, dtype=np.float64) * 0.5
                        + r).view(np.uint64)
                t0 = time.perf_counter()
                ns.write_many(ids, times, vals, tags)
                lat.append(time.perf_counter() - t0)
                write_dp += S
            if b == 0:  # warm flush: the seal + encode + volume write —
                # counted in the wall (throughput) but NOT in lat: p99
                # reports INGEST-round latency, not flush cost
                for shard in ns.shards.values():
                    shard.flush(bs)
        write_wall = time.perf_counter() - t_write0
        # RSS at end of ingest: the buffer-resident state (page pool vs
        # grow-arrays), before the read materializes result columns —
        # the two sides read different volumes (subset methodology), so
        # post-read RSS would not be comparable
        rss = rss_bytes()
        read_ids = ids if paged == "1" else ids[::64]
        t0 = time.perf_counter()
        out = ns.read_many(read_ids, START, START + 2 * BLOCK)
        read_wall = time.perf_counter() - t0
        read_dp = sum(len(t) for t, _ in out)
        read_rate = read_dp / read_wall if read_wall else 0.0
        full_read_dp = 2 * ROUNDS * S
        thr = (write_dp + full_read_dp) \
            / (write_wall + full_read_dp / max(read_rate, 1e-9))
        # correctness digest over the shared subset
        sub = out if paged != "1" else out[::64]
        digest = (sum(int(len(t)) for t, _ in sub),
                  sum(int(t.sum()) for t, _ in sub if len(t)),
                  sum(int(v.view(np.float64).sum()) for _, v in sub
                      if len(v)))
        db.close()
        return thr, float(np.quantile(lat, 0.99)), rss, digest

    # a 1M-series pair costs minutes; run interleaved pairs until the
    # wall budget is spent (≥1 pair always) and report the median pair
    budget_s = float(os.environ.get("M3_TPU_BENCH13_BUDGET_S", "360"))
    prev_paged = os.environ.get("M3_TPU_PAGED")
    try:
        with tempfile.TemporaryDirectory() as root:
            pairs = []
            meta = {}
            t_budget0 = time.perf_counter()
            for it in range(3):
                thr_p, p99_p, rss_p, dig_p = steady_state(
                    os.path.join(root, f"p{it}"), "1")
                thr_s, p99_s, rss_s, dig_s = steady_state(
                    os.path.join(root, f"s{it}"), "0")
                if dig_p != dig_s:
                    _emit("#13 paged 1M steady state (CORRECTNESS FAILED)",
                          0.0, 1.0)
                    return
                pairs.append((thr_p / thr_s, thr_p, thr_s))
                meta[thr_p / thr_s] = (p99_p, p99_s, rss_p, rss_s)
                if time.perf_counter() - t_budget0 > budget_s:
                    break
            pairs.sort(key=lambda p: p[0])
            ratio, thr_p, thr_s = pairs[len(pairs) // 2]
            p99_p, p99_s, rss_p, rss_s = meta[ratio]
            _emit(f"#13 paged write+read steady state {S} live series "
                  f"[p99 {p99_p * 1e3:.0f}ms vs {p99_s * 1e3:.0f}ms, RSS "
                  f"{rss_p >> 20}MB vs {rss_s >> 20}MB, paged vs "
                  f"grow-array; baseline read rate via 1/64 subset]",
                  thr_p, thr_s)
    finally:
        # steady_state exports the hatch per side: restore so a custom
        # --configs order never benchmarks later configs on the wrong path
        if prev_paged is None:
            os.environ.pop("M3_TPU_PAGED", None)
        else:
            os.environ["M3_TPU_PAGED"] = prev_paged

    # leg (b): the #12 shape, pipeline armed both sides, PAGED toggled
    S12 = max(int(160_000 * _scale()), 2048)
    NB, T = 12, 4
    with tempfile.TemporaryDirectory() as root:
        prev_pipe = os.environ.get("M3_TPU_PIPELINE")
        try:
            os.environ["M3_TPU_PAGED"] = "1"
            os.environ["M3_TPU_PIPELINE"] = "1"
            run = _sparse_multigroup_setup(root, S12, NB, T)
            n_dp = S12 * NB * T
            v_p = run()
            os.environ["M3_TPU_PAGED"] = "0"
            v_s = run()
            ok = (v_p.labels == v_s.labels
                  and np.array_equal(np.isnan(v_p.values),
                                     np.isnan(v_s.values))
                  and np.allclose(v_p.values, v_s.values, rtol=1e-9,
                                  atol=0, equal_nan=True))
            pairs = []
            for _ in range(7):
                os.environ["M3_TPU_PAGED"] = "1"
                t0 = time.perf_counter()
                run()
                dt_p = time.perf_counter() - t0
                os.environ["M3_TPU_PAGED"] = "0"
                t0 = time.perf_counter()
                run()
                dt_s = time.perf_counter() - t0
                pairs.append((dt_s / dt_p, n_dp / dt_p, n_dp / dt_s))
            pairs.sort(key=lambda p: p[0])
            _ratio, thr_p, thr_s = pairs[len(pairs) // 2]
            _emit(f"#13 ragged finalize e2e {S12} series x {NB} blocks "
                  f"[#12 shape, pipeline on, paged vs per-series "
                  f"concatenate]" + ("" if ok else " (CORRECTNESS FAILED)"),
                  thr_p, thr_s)
        finally:
            # RESTORE (not pop): an operator-pinned M3_TPU_PAGED must
            # survive into later configs of a custom --configs order
            if prev_paged is None:
                os.environ.pop("M3_TPU_PAGED", None)
            else:
                os.environ["M3_TPU_PAGED"] = prev_paged
            if prev_pipe is None:
                os.environ.pop("M3_TPU_PIPELINE", None)
            else:
                os.environ["M3_TPU_PIPELINE"] = prev_pipe


def config14_matcher_postings():
    """Device-compiled inverted index (ISSUE 16 / ROADMAP #4): boolean
    label-matcher evaluation over one packed segment at 1M and 10M
    terms — the fused ragged postings program (index/device.py: prefix-
    narrowed term resolution + ONE jit'd AND/OR/NOT combine over CSR
    rows) vs the PR-0 scalar walk reconstructed inline (per-term
    ``re.fullmatch`` over the full field vocabulary, pairwise sorted-
    array set ops).  Segment caches are cleared per evaluation so the
    device side pays matcher RESOLUTION every time; only the program-
    shape cache stays warm (that persistence is the design).  Pairing
    discipline as #11 (interleaved pairs, median pair reported), swept
    over the single-device and full virtual-mesh shard settings, and
    correctness-gated: the device doc-id sets must equal the scalar
    walk's exactly at every device count before anything is emitted."""
    import functools as _ft
    import re  # noqa: F401 - patterns below are compiled by the leaves

    import jax

    from m3_tpu.index import device, packed
    from m3_tpu.index import postings as P
    from m3_tpu.index.query import (
        ConjunctionQuery, DisjunctionQuery, NegationQuery, RegexpQuery,
        TermQuery,
    )
    from m3_tpu.index.segment import Document

    def scalar_leaf(seg, leaf):
        # the PR-0 walk: every term in the field pays a compiled-regex
        # fullmatch, every matched term pays a pairwise union
        if isinstance(leaf, TermQuery):
            return seg.postings_term(leaf.field_name, leaf.value)
        rx = leaf.compiled()
        out = P.EMPTY
        for t in seg.terms(leaf.field_name):
            if rx.fullmatch(t):
                out = P.union(out, seg.postings_term(leaf.field_name, t))
        return out

    def scalar_eval(seg, query):
        if isinstance(query, DisjunctionQuery):
            return _ft.reduce(P.union,
                              (scalar_leaf(seg, q) for q in query.queries),
                              P.EMPTY)
        pos = [q for q in query.queries
               if not isinstance(q, NegationQuery)]
        acc = _ft.reduce(P.intersect,
                         (scalar_leaf(seg, q) for q in pos))
        for q in query.queries:
            if isinstance(q, NegationQuery):
                acc = P.difference(acc, scalar_leaf(seg, q.inner))
        return acc

    def device_eval(seg, query):
        # resolution caches cleared: the device side re-pays term
        # bisect/narrowed regex scan per evaluation, like a cold query
        seg._regex_cache.clear()
        seg._term_idx_cache.clear()
        ids, reason = device.match(seg, query)
        if reason is not None:
            raise RuntimeError(f"unexpected fallback: {reason}")
        return ids

    n_devices = len(jax.devices())
    prev = {k: os.environ.get(k)
            for k in ("M3_TPU_DEVICE_OPS", "M3_TPU_INDEX_COMPILE",
                      "M3_TPU_QUERY_SHARD")}
    try:
        # pin the dispatch hatches: the bench isolates the two paths,
        # it does not re-test the work-threshold doctrine
        os.environ["M3_TPU_DEVICE_OPS"] = "1"
        os.environ["M3_TPU_INDEX_COMPILE"] = "1"
        for n in (max(int(1_000_000 * _scale()), 50_000),
                  max(int(10_000_000 * _scale()), 200_000)):
            seg = packed.build([
                Document(i, b"s%08d" % i,
                         [(b"pod", b"pod-%08d" % i),
                          (b"dc", b"dc-%d" % (i % 4)),
                          (b"app", b"app-%03d" % (i % 50))])
                for i in range(n)])
            # fixed-selectivity shapes (10k regex-matched terms at any
            # n >= 50k): conj regex+term, disj of regexes, conj with NOT
            queries = [
                ConjunctionQuery((RegexpQuery(b"pod", rb"pod-0000\d+"),
                                  TermQuery(b"dc", b"dc-1"))),
                DisjunctionQuery((RegexpQuery(b"pod", rb"pod-00001\d+"),
                                  RegexpQuery(b"pod", rb"pod-00002\d+"))),
                ConjunctionQuery((TermQuery(b"dc", b"dc-2"),
                                  NegationQuery(
                                      TermQuery(b"app", b"app-007")))),
            ]
            want = [scalar_eval(seg, q) for q in queries]
            shards = ["0"] + ([str(n_devices)] if n_devices > 1 else [])
            ok = True
            for shard in shards:  # gate at every device count
                os.environ["M3_TPU_QUERY_SHARD"] = shard
                got = [device_eval(seg, q) for q in queries]
                ok = ok and all(
                    np.array_equal(g.astype(np.int64), w.astype(np.int64))
                    for g, w in zip(got, want))
            n_dp = len(queries) * n
            sweep: list[str] = []
            headline = None
            for shard in shards:
                os.environ["M3_TPU_QUERY_SHARD"] = shard
                tag = "1dev" if shard == "0" else f"{shard}dev"
                # this mesh's executables were compiled by the gate pass;
                # interleaved pairs below measure steady-state serving
                pairs: list[tuple[float, float, float]] = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    for q in queries:
                        device_eval(seg, q)
                    dt_d = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    for q in queries:
                        scalar_eval(seg, q)
                    dt_h = time.perf_counter() - t0
                    pairs.append((dt_h / dt_d, n_dp / dt_d, n_dp / dt_h))
                pairs.sort(key=lambda p: p[0])
                med = pairs[len(pairs) // 2]
                sweep.append(f"{tag}:{med[0]:.2f}x")
                headline = med  # widest mesh is the recorded headline
            _ratio, thr_d, thr_h = headline
            _emit(f"#14 matcher postings {n}-term segment [3 boolean "
                  f"matcher queries, fused device program vs PR-0 scalar "
                  f"walk; sweep {' '.join(sweep)}]"
                  + ("" if ok else " (CORRECTNESS FAILED)"),
                  thr_d, thr_h)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def config15_tier_resolution():
    """Cheapest-tier read resolution (ISSUE 18 / ROADMAP #2): a 30-day
    dashboard query_range at a 1h step, served from the complete 1h
    aggregated tier (resolve_read routes the fetch there) vs the same
    query pinned to the raw namespace (M3_TPU_TIER_RESOLVE=0) decoding
    every 2m raw sample. Both sides run the same engine over the same
    Database; the ratio isolates exactly what tier routing changes: the
    sample count decoded (30x fewer at 2m->1h). Pairing discipline as
    #11/#14 (interleaved pairs, median pair reported) and correctness-
    gated before emission: label sets equal, NaN masks element-
    identical, values within 1e-9 relative — the tiers hold LAST-at-
    mark identical series so the instant-selector grids must agree
    exactly."""
    import tempfile

    from m3_tpu.query.engine import Engine
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.options import (
        DatabaseOptions, NamespaceOptions, RetentionOptions,
    )

    NS = 10**9
    MIN_NS = 60 * NS
    HOUR = 3600 * NS
    DAY = 24 * HOUR
    SAMP = 2 * MIN_NS
    DAYS = 30
    S = max(int(200 * _scale()), 8)
    T_RAW = DAYS * DAY // SAMP       # 21600 raw samples per series
    T_AGG = DAYS * DAY // HOUR       # 720 aggregated samples per series
    START = 1_600_000_000 * NS
    END = START + DAYS * DAY
    with tempfile.TemporaryDirectory() as root:
        db = Database(root, DatabaseOptions(n_shards=8))
        db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=40 * DAY,
                                       block_size_ns=2 * DAY),
            writes_to_commitlog=False, snapshot_enabled=False))
        db.create_namespace("aggregated_1h_365d", NamespaceOptions(
            retention=RetentionOptions(retention_ns=365 * DAY,
                                       block_size_ns=7 * DAY),
            aggregated_resolution_ns=HOUR, aggregated_complete=True,
            writes_to_commitlog=False, snapshot_enabled=False))
        db.open(now_ns=START)

        def value(i, t):
            # deterministic + LAST-at-mark: the raw value AT each hour
            # mark IS the tier's aggregate there, so both grids agree
            return float((t // SAMP + i * 37) % 1000)

        for ns_name, step_w in (("default", SAMP),
                                ("aggregated_1h_365d", HOUR)):
            entries = []
            for i in range(S):
                tags = [(b"host", b"h%04d" % i)]
                entries.extend(
                    (b"reqs", tags, t, value(i, t))
                    for t in range(START, END + 1, step_w))
            for lo in range(0, len(entries), 65536):
                db.write_batch(ns_name, entries[lo:lo + 65536])

        eng = Engine(db, "default", now_fn=lambda: END)
        n_dp = S * T_RAW  # raw samples the pinned path decodes

        def run():
            return eng.query_range("reqs", START + HOUR, END, HOUR)[0]

        prev = os.environ.get("M3_TPU_TIER_RESOLVE")
        try:
            os.environ.pop("M3_TPU_TIER_RESOLVE", None)
            v_t = run()  # tier-routed (warm)
            os.environ["M3_TPU_TIER_RESOLVE"] = "0"
            v_r = run()  # raw-pinned (warm)
            key = lambda d: sorted(d.items())  # noqa: E731
            ot = np.argsort([str(key(d)) for d in v_t.labels])
            orr = np.argsort([str(key(d)) for d in v_r.labels])
            tv, rv = v_t.values[ot], v_r.values[orr]
            ok = ([key(v_t.labels[i]) for i in ot]
                  == [key(v_r.labels[i]) for i in orr]
                  and np.array_equal(np.isnan(tv), np.isnan(rv))
                  and np.allclose(tv, rv, rtol=1e-9, atol=0,
                                  equal_nan=True))
            pairs: list[tuple[float, float, float]] = []
            for _ in range(5):
                os.environ.pop("M3_TPU_TIER_RESOLVE", None)
                t0 = time.perf_counter()
                run()
                dt_t = time.perf_counter() - t0
                os.environ["M3_TPU_TIER_RESOLVE"] = "0"
                t0 = time.perf_counter()
                run()
                dt_r = time.perf_counter() - t0
                pairs.append((dt_r / dt_t, n_dp / dt_t, n_dp / dt_r))
            pairs.sort(key=lambda p: p[0])
            _ratio, thr_t, thr_r = pairs[len(pairs) // 2]
            _emit(f"#15 tier-resolved 30d query_range @1h step, {S} series "
                  f"[aggregated 1h tier ({T_AGG}/series) vs raw 2m decode "
                  f"({T_RAW}/series)]"
                  + ("" if ok else " (CORRECTNESS FAILED)"),
                  thr_t, thr_r)
        finally:
            if prev is None:
                os.environ.pop("M3_TPU_TIER_RESOLVE", None)
            else:
                os.environ["M3_TPU_TIER_RESOLVE"] = prev


def config16_compute_overhead():
    """Device-compute observability overhead guard (this PR): the
    write+query hot path with the execute-telemetry ledger ARMED
    (every tracked jit_tracker exit attributing wall time into
    compute_stats — per-program execute histograms, the ranked program
    table, padding-waste records, eviction ground-truth bookkeeping)
    vs DISARMED (``compute_stats.arm(False)``: every record_* returns
    at the flag check — the seed-equivalent cost). Same pairing
    discipline as #7/#10: interleaved on/off pairs, median of per-pair
    ratios, flagged below 0.85.

    The workload is one run_once = a hot-buffer write burst (the
    ingest side the tracker must never tax) followed by compiled
    query_range evaluations on the cache-HIT path — the exact site
    where record_execute/record_waste fire per call."""
    import tempfile

    from m3_tpu.encoding.m3tsz import hostpath
    from m3_tpu.query.engine import Engine
    from m3_tpu.storage.database import Database
    from m3_tpu.storage.fileset import FilesetWriter
    from m3_tpu.storage.options import (
        DatabaseOptions, IndexOptions, NamespaceOptions, RetentionOptions,
    )
    from m3_tpu.utils import compute_stats
    from m3_tpu.utils.xtime import TimeUnit

    NS = 10**9
    BLOCK = 24 * 3600 * NS
    START = 1_600_000_000 * NS
    S = max(int(2_000 * _scale()), 200)
    SAMP = 300 * NS
    T = BLOCK // SAMP              # 288 samples per series
    W = max(int(60_000 * _scale()), 6_000)   # write burst per run
    with tempfile.TemporaryDirectory() as root:
        db = Database(root, DatabaseOptions(
            n_shards=4, block_cache_entries=100_000))
        ns = db.create_namespace("default", NamespaceOptions(
            retention=RetentionOptions(retention_ns=1000 * BLOCK,
                                       block_size_ns=BLOCK),
            index=IndexOptions(enabled=True, block_size_ns=BLOCK),
            writes_to_commitlog=False, snapshot_enabled=False))
        ids = [b"reqs,host=h%03d,i=%05d" % (i % 50, i) for i in range(S)]
        fields = [[(b"__name__", b"reqs"), (b"host", b"h%03d" % (i % 50)),
                   (b"i", b"%05d" % i)] for i in range(S)]
        by_shard: dict[int, list[int]] = {}
        for j, sid in enumerate(ids):
            by_shard.setdefault(ns.shard_set.lookup(sid), []).append(j)
        rng = np.random.default_rng(0)
        for shard_id, rows in by_shard.items():
            nb = len(rows)
            times = np.broadcast_to(
                START + np.arange(T, dtype=np.int64) * SAMP, (nb, T)).copy()
            vals = rng.integers(1, 10, (nb, T)).astype(np.float64) \
                .cumsum(axis=1)
            streams = hostpath.encode_blocks(
                times, vals.view(np.uint64), np.full(nb, START, np.int64),
                np.full(nb, T, np.int32), TimeUnit.SECOND, False)
            w = FilesetWriter(db.fs_root, "default", shard_id, START,
                              BLOCK, 0)
            for j, stream in zip(rows, streams):
                w.write_series(ids[j], b"", stream)
            w.close()
        db.open(START + BLOCK)
        ns.index.insert_many(ids, fields, np.full(S, START, np.int64))
        eng = Engine(db, resolve_tiers=False)
        qstart = START + 30 * 60 * NS
        qend = START + BLOCK - SAMP
        step = 2 * 60 * NS
        q = "max by (host) (irate(reqs[30m]))"
        wtags = [(b"k", b"v")]
        wnames = [b"w%04d" % i for i in range(500)]
        n_dp = S * T  # samples each query reads

        def run_once() -> float:
            t0 = time.perf_counter()
            for i in range(W):  # hot-buffer ingest leg (active block)
                db.write_tagged("default", wnames[i % 500], wtags,
                                START + BLOCK + (i % 3600) * NS, float(i))
            for _ in range(2):  # compiled cache-HIT query leg
                eng.query_range(q, qstart, qend, step)
            return (W + 2 * n_dp) / (time.perf_counter() - t0)

        prev = os.environ.get("M3_TPU_QUERY_COMPILE")
        os.environ["M3_TPU_QUERY_COMPILE"] = "1"
        ratios: list[float] = []
        rate_on = rate_off = 0.0
        try:
            compute_stats.arm(True)
            run_once()  # warm: pays the plan + postings compiles once
            for _ in range(5):
                compute_stats.arm(True)
                on = run_once()
                compute_stats.arm(False)
                off = run_once()
                ratios.append(on / off)
                rate_on, rate_off = max(rate_on, on), max(rate_off, off)
        finally:
            compute_stats.arm(
                os.environ.get("M3_TPU_COMPUTE_STATS", "1") != "0")
            if prev is None:
                os.environ.pop("M3_TPU_QUERY_COMPILE", None)
            else:
                os.environ["M3_TPU_QUERY_COMPILE"] = prev
        db.close()
    ratios.sort()
    ratio = ratios[len(ratios) // 2]
    _emit("#16 write+query hot path w/ device-compute telemetry armed "
          "vs disarmed"
          + ("" if ratio >= 0.85 else " (OVERHEAD EXCEEDED)"),
          ratio * rate_off, rate_off)


def config17_wire_read():
    """Binary wire format (ISSUE 20 / ROADMAP #1): coordinator fanout
    read over real HTTP sockets — the packed read_batch frame (ragged
    CSR offsets + m3tsz-re-encoded sample columns, utils/wire) vs the
    legacy float64-JSON rows the M3_TPU_WIRE=json hatch pins. Bytes on
    the wire are read off the client-side net.bytes.{sent,recv}
    {flow=read_batch} counters (the satellite accounting this PR adds),
    so the ratio measures exactly what a fleet's NIC sees. Correctness
    is gated on EXACT sample equality (default precision is exact —
    m3tsz re-encode round-trips bit-identical float64) before anything
    is emitted; the emitted line carries the bytes reduction in the
    metric name and packed-vs-json fetch throughput as value/baseline,
    so both acceptance axes (>=3x fewer bytes, QPS no worse) live in
    one recorded line."""
    import tempfile

    from m3_tpu.client.http_conn import HTTPNodeConnection
    from m3_tpu.client.session import Session
    from m3_tpu.cluster import placement as pl
    from m3_tpu.cluster.kv import KVStore
    from m3_tpu.cluster.placement import Instance, initial_placement
    from m3_tpu.cluster.topology import ConsistencyLevel, TopologyMap
    from m3_tpu.services.dbnode import DBNodeService
    from m3_tpu.utils.ident import tags_to_id
    from m3_tpu.utils.instrument import default_registry

    NS = 10**9
    START = 1_600_000_000 * NS
    S = max(int(1_000 * _scale()), 100)
    T = 360  # one hour at 10s resolution
    n_dp = S * T

    reg = default_registry()

    def net_bytes() -> float:
        total = 0.0
        for d in ("sent", "recv"):
            c = reg.counters.get(
                (f"net.bytes.{d}", (("flow", "read_batch"),)))
            total += c.value if c is not None else 0.0
        return total

    prev = os.environ.get("M3_TPU_WIRE")
    with tempfile.TemporaryDirectory() as root:
        kv = KVStore()
        p = initial_placement([Instance("n0", isolation_group="g0")],
                              n_shards=4, replica_factor=1)
        p = pl.mark_available(p, "n0")
        pl.store_placement(kv, p)
        svc = DBNodeService(
            {"db": {"path": root, "n_shards": 4,
                    "namespaces": [{"name": "default"}]},
             "cluster": {"instance_id": "n0"}}, kv=kv)
        svc.db.open(START)
        svc.sync_placement()
        port = svc.api.serve(host="127.0.0.1", port=0)

        def set_endpoint(cur):
            cur.instances["n0"].endpoint = f"http://127.0.0.1:{port}"
            return cur

        pl.cas_update_placement(kv, set_endpoint)
        p, _ = pl.load_placement(kv)
        sess = Session(
            TopologyMap(p),
            {iid: HTTPNodeConnection(inst.endpoint)
             for iid, inst in p.instances.items()},
            write_consistency=ConsistencyLevel.ALL,
            read_consistency=ConsistencyLevel.ONE)
        # counter-style series: regular 10s cadence, small integer-ish
        # increments — the fleet shape m3tsz was built for
        sids = []
        for i in range(S):
            tags = [(b"host", b"h%04d" % i)]
            sids.append(tags_to_id(b"reqs", tags))
            for k in range(T):
                svc.db.write_tagged(
                    "default", b"reqs", tags, START + k * 10 * NS,
                    float((k * 7 + i) % 120))

        def fetch():
            return sess.fetch_many("default", sids, START,
                                   START + 3600 * NS)

        try:
            os.environ.pop("M3_TPU_WIRE", None)  # default: packed
            packed = fetch()  # warm
            b0 = net_bytes()
            packed = fetch()
            bytes_packed = net_bytes() - b0
            t0 = time.perf_counter()
            for _ in range(3):
                fetch()
            dt_packed = (time.perf_counter() - t0) / 3

            os.environ["M3_TPU_WIRE"] = "json"
            legacy = fetch()  # warm
            b0 = net_bytes()
            legacy = fetch()
            bytes_json = net_bytes() - b0
            t0 = time.perf_counter()
            for _ in range(3):
                fetch()
            dt_json = (time.perf_counter() - t0) / 3
        finally:
            if prev is None:
                os.environ.pop("M3_TPU_WIRE", None)
            else:
                os.environ["M3_TPU_WIRE"] = prev
            svc.api.shutdown()
            svc.db.close()

    ok = (len(packed) == len(legacy) == S
          and sum(len(t) for t, _ in packed) == n_dp
          and all(np.array_equal(ta, tb) and np.array_equal(va, vb)
                  for (ta, va), (tb, vb) in zip(packed, legacy)))
    bratio = bytes_json / bytes_packed if bytes_packed else 0.0
    _emit(f"#17 wire read_batch {S} series x {T} pts over HTTP "
          f"[packed CSR+m3tsz vs json, {bratio:.1f}x fewer bytes]"
          + ("" if ok else " (CORRECTNESS FAILED)")
          + ("" if bratio >= 3.0 else " (BYTES TARGET MISSED)"),
          n_dp / dt_packed, n_dp / dt_json)


def main(argv=None) -> None:
    global _ACCEL
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs",
                    default="1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17")
    ap.add_argument("--record", default=None,
                    help="also append the JSON lines to this file")
    args = ap.parse_args(argv)
    if os.environ.get(_CHILD_ENV) != "1":
        from m3_tpu.utils import tpu_preflight
        from m3_tpu.utils.childproc import scrubbed_env

        if tpu_preflight.probe().live:
            _ACCEL = True  # run in-process against the live tunnel
        else:
            # dead tunnel: re-exec with a scrubbed env (see module doc);
            # 8 virtual CPU devices so config #11 sweeps the full series
            # mesh and #5 still exercises its 4-shard shard_map + psum
            env = scrubbed_env(n_devices=8)
            env[_CHILD_ENV] = "1"
            cmd = [sys.executable, "-m", "m3_tpu.tools.bench_all",
                   "--configs", args.configs]
            if args.record:
                cmd += ["--record", args.record]
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            raise SystemExit(subprocess.run(cmd, env=env, cwd=repo).returncode)
    fns = {"1": config1_codec_roundtrip, "2": config2_rollup,
           "3": config3_promql_rate_sum, "4": config4_regex_postings,
           "5": config5_sharded_quantile, "6": config6_read_many,
           "7": config7_tracing_overhead, "8": config8_write_batch,
           "9": config9_query_compile, "10": config10_profiler_overhead,
           "11": config11_sharded_query, "12": config12_pipelined_read,
           "13": config13_paged_memory, "14": config14_matcher_postings,
           "15": config15_tier_resolution,
           "16": config16_compute_overhead, "17": config17_wire_read}
    for c in args.configs.split(","):
        c = c.strip()
        try:
            fns[c]()
        except Exception as e:  # noqa: BLE001 - one config must not kill the rest
            print(json.dumps({"metric": f"#{c} failed: {e}"[:200],
                              "value": 0.0, "unit": "M datapoints/sec",
                              "vs_baseline": 0.0}), flush=True)
    if args.record:
        # append, as documented: a partial-config run (--configs 9) must
        # not clobber the other configs' recorded history
        with open(args.record, "a") as f:
            for line in _RECORD:
                f.write(json.dumps(line) + "\n")


if __name__ == "__main__":
    main()
