"""Fileset / commitlog inspectors and verifiers.

Role parity with the reference operator tools
(/root/reference/src/cmd/tools: read_data_files, read_index_files,
verify_data_files, and the commitlog reader):

  python -m m3_tpu.tools.inspect list     <data_root> <namespace>
  python -m m3_tpu.tools.inspect info     <data_root> <namespace> <shard> <block_start>
  python -m m3_tpu.tools.inspect read     <data_root> <namespace> <shard> <block_start> [series_id]
  python -m m3_tpu.tools.inspect verify   <data_root> <namespace>
  python -m m3_tpu.tools.inspect commitlog <path>
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from m3_tpu.encoding.m3tsz import decode as m3tsz_decode
from m3_tpu.storage import commitlog
from m3_tpu.storage.fileset import FilesetReader, list_filesets
from m3_tpu.utils.ident import decode_tags
from m3_tpu.utils.xtime import TimeUnit


def cmd_list(root: str, namespace: str) -> int:
    ns_dir = os.path.join(root, namespace)
    if not os.path.isdir(ns_dir):
        print(f"no such namespace dir {ns_dir}", file=sys.stderr)
        return 1
    shards = sorted((s for s in os.listdir(ns_dir) if s.isdigit()), key=int)
    for shard in shards:
        for bs, vol in list_filesets(root, namespace, int(shard)):
            r = FilesetReader(root, namespace, int(shard), bs, vol, verify=False)
            print(json.dumps({
                "shard": int(shard), "block_start": bs, "volume": vol,
                "n_series": r.n_series, "data_bytes": r.info["data_length"],
            }))
            r.close()
    return 0


def cmd_info(root, namespace, shard, block_start) -> int:
    for bs, vol in list_filesets(root, namespace, shard):
        if bs == block_start:
            r = FilesetReader(root, namespace, shard, bs, vol, verify=False)
            print(json.dumps(r.info, indent=2))
            r.close()
            return 0
    print("fileset not found", file=sys.stderr)
    return 1


def cmd_read(root, namespace, shard, block_start, series_id=None,
             unit=TimeUnit.SECOND) -> int:
    vols = dict(list_filesets(root, namespace, shard))
    if block_start not in vols:
        print("fileset not found", file=sys.stderr)
        return 1
    r = FilesetReader(root, namespace, shard, block_start, vols[block_start])
    try:
        want = series_id.encode() if series_id else None
        found = False
        for i in range(r.n_series):
            sid, tags_blob, stream = r.read_at(i)
            if want is not None and sid != want:
                continue
            found = True
            tags = (
                {k.decode(errors="replace"): v.decode(errors="replace")
                 for k, v in decode_tags(tags_blob)}
                if tags_blob else {}
            )
            dps = m3tsz_decode(stream, int_optimized=False,
                               default_time_unit=unit)
            print(json.dumps({
                "series_id": sid.decode(errors="replace"),
                "tags": tags,
                "bytes": len(stream),
                "datapoints": [[d.timestamp_ns, d.value] for d in dps],
            }))
        if want is not None and not found:
            print(f"series not found: {want!r}", file=sys.stderr)
            return 1
    finally:
        r.close()
    return 0


def cmd_verify(root, namespace, unit=TimeUnit.SECOND) -> int:
    """Digest-verify every complete fileset and decode every stream."""
    ns_dir = os.path.join(root, namespace)
    if not os.path.isdir(ns_dir):
        print(f"no such namespace dir {ns_dir}", file=sys.stderr)
        return 1
    bad = total = 0
    for shard in sorted(os.listdir(ns_dir)):
        if not shard.isdigit():
            continue
        for bs, vol in list_filesets(root, namespace, int(shard)):
            total += 1
            r = None
            try:
                r = FilesetReader(root, namespace, int(shard), bs, vol, verify=True)
                for i in range(r.n_series):
                    sid, _tags, stream = r.read_at(i)
                    m3tsz_decode(stream, int_optimized=False,
                                 default_time_unit=unit)
            except Exception as e:
                bad += 1
                print(json.dumps({
                    "shard": int(shard), "block_start": bs, "volume": vol,
                    "error": str(e),
                }))
            finally:
                if r is not None:
                    r.close()
    print(json.dumps({"filesets": total, "corrupt": bad}))
    return 1 if bad else 0


def cmd_commitlog(path: str) -> int:
    for e in commitlog.replay(path):
        print(json.dumps({
            "series_id": e.series_id.decode(errors="replace"),
            "t_ns": e.time_ns,
            "value_bits": e.value_bits,
            "unit": e.unit,
        }))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="m3_tpu.tools.inspect")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list")
    p.add_argument("root")
    p.add_argument("namespace")
    p = sub.add_parser("info")
    p.add_argument("root")
    p.add_argument("namespace")
    p.add_argument("shard", type=int)
    p.add_argument("block_start", type=int)
    p = sub.add_parser("read")
    p.add_argument("root")
    p.add_argument("namespace")
    p.add_argument("shard", type=int)
    p.add_argument("block_start", type=int)
    p.add_argument("series_id", nargs="?")
    p.add_argument("--unit", default="SECOND",
                   help="block write time unit (SECOND/MILLISECOND/...)")
    p = sub.add_parser("verify")
    p.add_argument("root")
    p.add_argument("namespace")
    p.add_argument("--unit", default="SECOND")
    p = sub.add_parser("commitlog")
    p.add_argument("path")
    args = ap.parse_args(argv)
    if args.cmd == "list":
        return cmd_list(args.root, args.namespace)
    if args.cmd == "info":
        return cmd_info(args.root, args.namespace, args.shard, args.block_start)
    if args.cmd == "read":
        return cmd_read(args.root, args.namespace, args.shard, args.block_start,
                        args.series_id, TimeUnit[args.unit.upper()])
    if args.cmd == "verify":
        return cmd_verify(args.root, args.namespace, TimeUnit[args.unit.upper()])
    if args.cmd == "commitlog":
        return cmd_commitlog(args.path)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
